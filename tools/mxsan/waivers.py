"""Registry waivers for mxsan witness findings.

A finding here judges runtime behaviour, so there is no source line to
carry an inline suppression; deliberate exceptions are waived centrally
as (rule, finding-key glob, reason).

Rules of the registry (the shardlint contract):
  * every entry carries a reason — an empty reason never waives and is
    a test failure;
  * the list is BUDGETED: tests/test_mxsan.py pins the exact entries
    and caps the count at 5, so a waiver is a reviewed, deliberate
    exception, not a pressure valve.

Finding keys by rule:
  SAN01  "siteA -> siteB -> ... -> siteA"   (the cycle path)
  SAN02  "siteA -> siteB"                   (the observed edge)
  SAN03  "kind @ site"                      (e.g. "time.sleep @ ...")
  SAN04  "site"
  SAN05  thread name

Sites are spelled ``<module>:<lock name>`` exactly as in
tools/mxlint/lock_order.py.
"""

WAIVERS = []
