"""mxsan analyzer: judge a witness snapshot against lock_order.py.

The runtime half (``incubator_mxnet_tpu/mxsan.py``) records what
threads actually did — lock-order edges with acquisition stacks,
blocking calls made under a lock, re-entry attempts, thread lifecycle
rows.  This package is the judgement half: pure stdlib, never imports
the package under test (mirroring tools/mxlint), so it can replay a
witness log from any process.

Rules:
  SAN01  observed lock-order cycle (AB/BA potential deadlock)
  SAN02  observed edge contradicts lock_order.py (undeclared lock,
         inverted order, or an undeclared cross-module nesting)
  SAN03  blocking call while holding a lock
  SAN04  re-entry attempt on a non-reentrant lock
  SAN05  thread lifecycle (non-``mxtpu-*`` name, leaked non-daemon)

Waivers mirror shardlint's registry contract: (rule, key-glob, reason)
tuples in ``tools/mxsan/waivers.py``, reason required, budget pinned
EXACT by tests/test_mxsan.py.
"""
from __future__ import annotations

import fnmatch
import json

from tools.mxlint.lock_order import (BLOCKING_OK, CROSS_MODULE_EDGES,
                                     LOCK_ORDER)

__all__ = ["RULES", "Finding", "SanResult", "analyze", "load_witness",
           "declared_edge_count"]

RULES = {
    "SAN01": ("observed lock-order cycle",
              "two lock chains close a loop: some thread interleaving "
              "deadlocks. Break the cycle by acquiring in one order."),
    "SAN02": ("observed edge contradicts lock_order.py",
              "a real thread nested locks in an order the registry "
              "does not declare. Declare the nesting (cross-module "
              "edges go in CROSS_MODULE_EDGES) or fix the code."),
    "SAN03": ("blocking call while holding a lock",
              "sleep/join/un-timed get/subprocess/socket under a lock "
              "stalls every waiter. Move the wait outside the lock or "
              "add the site to BLOCKING_OK with a justification."),
    "SAN04": ("re-entry on a non-reentrant lock",
              "the holding thread re-acquired a plain Lock: guaranteed "
              "self-deadlock once the timeout is removed. Split the "
              "function or use the *_locked-callee convention."),
    "SAN05": ("thread lifecycle violation",
              "threads need an mxtpu-* name and must be daemon or "
              "joined; an anonymous live non-daemon thread outlives "
              "its owner silently."),
}


class Finding:
    """One judged violation: rule id, a stable key the waiver globs
    match against, a one-line message, and the witness detail (stacks,
    threads) for the report."""

    def __init__(self, rule, key, message, detail=None):
        self.rule = rule
        self.key = key
        self.message = message
        self.detail = detail or {}
        self.waive_reason = None

    def render(self):
        title = RULES[self.rule][0]
        out = ["%s [%s]: %s — %s" % (self.rule, self.key, title,
                                     self.message)]
        for label, row in sorted(self.detail.get("stacks", {}).items()):
            out.append("  %s (thread %s):" % (label, row.get("thread", "?")))
            for frame in row.get("stack", ()):
                out.append("    %s" % frame)
        return "\n".join(out)

    def as_dict(self):
        return {"rule": self.rule, "key": self.key,
                "message": self.message, "detail": self.detail,
                "waive_reason": self.waive_reason}


class SanResult:
    def __init__(self, findings, waived, stats):
        self.findings = findings
        self.waived = waived
        self.stats = stats

    @property
    def clean(self):
        return not self.findings

    def as_dict(self):
        return {
            "clean": self.clean,
            "findings": [f.as_dict() for f in self.findings],
            "waived": [f.as_dict() for f in self.waived],
            "stats": dict(self.stats),
        }


def declared_edge_count():
    """Orderable pairs the registry declares: every within-module pair
    LOCK_ORDER permits plus every cross-module edge."""
    n = len(CROSS_MODULE_EDGES)
    for order in LOCK_ORDER.values():
        n += len(order) * (len(order) - 1) // 2
    return n


def load_witness(path):
    """Read a witness log written by ``mxsan.dump`` (raises ValueError
    on a structurally unusable file)."""
    with open(path) as f:
        snap = json.load(f)
    if not isinstance(snap, dict) or "edges" not in snap:
        raise ValueError("not a mxsan witness log (no 'edges' table)")
    return snap


def _site(raw):
    """Split ``module:lock`` (the mxsan site spelling)."""
    if ":" in raw:
        return raw.split(":", 1)
    return "", raw


def _check_edge(a, b):
    """SAN02 message for observed edge a->b, or None if declared."""
    mod_a, name_a = _site(a)
    mod_b, name_b = _site(b)
    if mod_a != mod_b:
        if (a, b) in CROSS_MODULE_EDGES:
            return None
        return ("cross-module nesting %s -> %s is not declared in "
                "CROSS_MODULE_EDGES" % (a, b))
    order = LOCK_ORDER.get(mod_a)
    if order is None:
        return ("module %s holds nested locks but has no lock_order.py "
                "entry" % mod_a)
    missing = [n for n in (name_a, name_b) if n not in order]
    if missing:
        return ("lock%s %s of %s absent from the declared order" %
                ("s" if len(missing) > 1 else "",
                 ", ".join(missing), mod_a))
    if order.index(name_a) >= order.index(name_b):
        return ("observed %s -> %s inverts the declared order (%s)" %
                (name_a, name_b, ", ".join(order)))
    return None


def analyze(witness, waivers=None):
    """Judge one witness snapshot (live ``mxsan.witness()`` dict or a
    replayed log). ``waivers=None`` uses the in-tree registry; pass
    ``()`` to disable."""
    if waivers is None:
        from .waivers import WAIVERS
        waivers = WAIVERS
    findings = []

    for cyc in witness.get("cycles", ()):
        key = " -> ".join(cyc.get("path", ()))
        n = len(cyc.get("edges", ()))
        findings.append(Finding(
            "SAN01", key,
            "%d-edge cycle closed by thread %s; every edge's first "
            "acquisition stack follows" % (n, cyc.get("thread", "?")),
            {"stacks": cyc.get("stacks", {})}))

    for edge in witness.get("edges", ()):
        a, b = edge["a"], edge["b"]
        msg = _check_edge(a, b)
        if msg is not None:
            key = "%s -> %s" % (a, b)
            findings.append(Finding(
                "SAN02", key,
                "%s (seen %dx, thread %s)" % (msg, edge.get("count", 1),
                                              edge.get("thread", "?")),
                {"stacks": {key: {"thread": edge.get("thread", "?"),
                                  "stack": edge.get("stack", [])}}}))

    for row in witness.get("blocking", ()):
        site = row["site"]
        if site in BLOCKING_OK:
            continue
        key = "%s @ %s" % (row["kind"], site)
        findings.append(Finding(
            "SAN03", key,
            "%s called %dx while holding %s" %
            (row["kind"], row.get("count", 1),
             ", ".join(row.get("held", (site,)))),
            {"stacks": {key: {"thread": row.get("thread", "?"),
                              "stack": row.get("stack", [])}}}))

    for row in witness.get("reentry", ()):
        site = row["site"]
        findings.append(Finding(
            "SAN04", site,
            "thread %s re-acquired non-reentrant %s (%dx)" %
            (row.get("thread", "?"), site, row.get("count", 1)),
            {"stacks": {site: {"thread": row.get("thread", "?"),
                               "stack": row.get("stack", [])}}}))

    for row in witness.get("threads", ()):
        findings.append(Finding(
            "SAN05", row.get("name", ""),
            "thread %r (daemon=%s, alive=%s): %s" %
            (row.get("name", ""), row.get("daemon"), row.get("alive"),
             ", ".join(row.get("problems", ())))))

    kept, waived = [], []
    for f in findings:
        reason = _waive_reason(f, waivers)
        if reason:
            f.waive_reason = reason
            waived.append(f)
        else:
            kept.append(f)
    order = sorted(RULES)
    kept.sort(key=lambda f: (order.index(f.rule), f.key))
    return SanResult(kept, waived, witness.get("stats", {}))


def _waive_reason(finding, waivers):
    for rule, pattern, reason in waivers:
        # an empty reason never waives: the registry contract (and the
        # budget test) requires each entry to justify itself
        if reason and rule == finding.rule and \
                fnmatch.fnmatchcase(finding.key, pattern):
            return reason
    return None
