"""mxsan CLI: replay a recorded witness log against lock_order.py.

    python -m tools.mxsan WITNESS.json [--format=text|json]
                          [--list] [--no-waivers]

The log is written by the runtime half: run the workload with
``MXNET_MXSAN=1 MXNET_MXSAN_LOG=/path/witness.json`` (or call
``mxsan.dump(path)`` at drain) and replay it here — the analyzer is
pure stdlib and never imports the package, so the judgement can run on
a machine that cannot.

Exit status: 0 clean, 1 findings, 2 usage error (missing or
structurally invalid log).
"""
from __future__ import annotations

import argparse
import json
import sys

from . import RULES, analyze, declared_edge_count, load_witness


def _render_text(result):
    for f in result.findings:
        print(f.render())
    n, w = len(result.findings), len(result.waived)
    print("mxsan: %d observed edge%s (%d declared orderable), "
          "%d finding%s, %d waived" %
          (result.stats.get("edges_observed", 0),
           "" if result.stats.get("edges_observed", 0) == 1 else "s",
           declared_edge_count(),
           n, "" if n == 1 else "s", w))
    for f in result.waived:
        print("  waived %s on %s (%s)" % (f.rule, f.key, f.waive_reason))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxsan",
        description="witness-based lock-order sanitizer (replay half)")
    ap.add_argument("witness", nargs="?", default=None,
                    help="witness log written by mxsan.dump / "
                         "MXNET_MXSAN_LOG")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list", action="store_true",
                    help="list rules and waivers, then exit")
    ap.add_argument("--no-waivers", action="store_true",
                    help="judge with the waiver registry disabled")
    args = ap.parse_args(argv)

    if args.list:
        from .waivers import WAIVERS
        print("rules:")
        for rule, (title, _hint) in sorted(RULES.items()):
            print("  %s: %s" % (rule, title))
        print("waivers: %d" % len(WAIVERS))
        for rule, glob, reason in WAIVERS:
            print("  %s on %s: %s" % (rule, glob, reason))
        return 0

    if not args.witness:
        print("mxsan: a witness log is required (see --help)",
              file=sys.stderr)
        return 2
    try:
        snap = load_witness(args.witness)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("mxsan: cannot read witness %s: %s" % (args.witness, e),
              file=sys.stderr)
        return 2

    result = analyze(snap, waivers=() if args.no_waivers else None)
    result.stats = dict(result.stats,
                        edges_observed=len(snap.get("edges", ())))
    if args.format == "json":
        print(json.dumps(result.as_dict(), indent=2, sort_keys=True))
    else:
        _render_text(result)
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
