#!/usr/bin/env python
"""Rebuild the .idx sidecar for a .rec file (reference tools/rec2idx.py).

Uses the native C++ scanner (native/recordio.cc mxtpu_recordio_index) when
available, else a pure-python scan."""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("record", help="path to .rec file")
    ap.add_argument("index", nargs="?", default=None,
                    help="output .idx (default: record with .idx suffix)")
    args = ap.parse_args()
    idx = args.index or os.path.splitext(args.record)[0] + ".idx"

    from incubator_mxnet_tpu import native, recordio
    n = None
    try:
        n = native.build_index(args.record, idx)
    except Exception:
        n = None
    if n is None:  # python fallback
        os.environ["MXTPU_NO_NATIVE"] = "1"
        r = recordio.MXRecordIO(args.record, "r")
        with open(idx, "w") as f:
            n = 0
            while True:
                pos = r.tell()
                if r.read() is None:
                    break
                f.write(f"{n}\t{pos}\n")
                n += 1
        r.close()
    print(f"[rec2idx] {n} records -> {idx}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
