#!/usr/bin/env python
"""Rerun a test many times with different seeds to expose flakiness
(reference tools/flakiness_checker.py: N trials under random MXNET_TEST_SEED).

Usage:
    python tools/flakiness_checker.py tests/test_operator.py::test_topk -n 50
"""
from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("test", help="pytest node id (file[::test])")
    ap.add_argument("-n", "--trials", type=int, default=20)
    ap.add_argument("--seed", type=int, default=None,
                    help="fixed base seed (default: random per trial)")
    ap.add_argument("--stop-on-fail", action="store_true")
    args = ap.parse_args()

    failures = []
    for i in range(args.trials):
        seed = args.seed if args.seed is not None else \
            random.randint(1, 2**31 - 1)
        env = dict(os.environ, MXTPU_TEST_SEED=str(seed))
        r = subprocess.run([sys.executable, "-m", "pytest", args.test,
                            "-x", "-q"], env=env, capture_output=True,
                           text=True)
        status = "PASS" if r.returncode == 0 else "FAIL"
        print(f"[{i + 1}/{args.trials}] seed={seed} {status}")
        if r.returncode != 0:
            failures.append(seed)
            sys.stderr.write(r.stdout[-2000:] + "\n")
            if args.stop_on_fail:
                break
    print(f"\n{len(failures)}/{args.trials} trials failed"
          + (f"; failing seeds: {failures}" if failures else ""))
    print("reproduce with: MXTPU_TEST_SEED=<seed> python -m pytest", args.test)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
