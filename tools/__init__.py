# tools/ is importable so `python -m tools.mxlint` and the `mxlint`
# console script resolve; the other entries here stay plain scripts.
