#!/usr/bin/env python
"""Schema checker for profiler chrome-trace dumps.

chrome://tracing and Perfetto fail *silently* on malformed traces (events
just vanish from the timeline), so "the file loads" is not a test. This
validates the subset of the Trace Event Format the profiler emits —
https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
— and is what tests/test_profiler.py asserts against.

Checked invariants:
  * top level is {"traceEvents": [...]} (dict events)
  * every event has string "name"/"ph" and numeric "ts" >= 0
  * "ph" is one of the phases the profiler emits: X, i, C, M
  * X (complete) events carry a numeric "dur" >= 0
  * i (instant) events carry no "dur"; an "s" flag must be p/t/g
  * C (counter) events carry numeric args values (the counter track)
  * "pid"/"tid", when present, are int or string

Attribution-span invariants (X events whose args carry "span_id" — the
step-time attribution layer, including merged multi-process timelines
from tools/trace_merge.py):
  * span_id is a positive int, unique within its (pid, trace) scope
  * "parent", when present, is a positive int; when the parent span is in
    the same file, the child's [ts, ts+dur] interval must lie inside the
    parent's (a parent flushed into an earlier rolling segment is
    tolerated — the child exits before the parent books itself)
  * "clock_sync" metadata events carry numeric offset_us / rtt_us /
    perf_anchor_us / wall_anchor_us (what trace_merge aligns clocks with)
  * "remote_profile" metadata events (stamped by fleetobs on traces a
    rank ships back over the kvstore wire) carry an int rank >= 0, a
    positive int request_id, and int steps/segments >= 0

Request-span invariants (X events whose args carry "req_trace" — the
serve/reqtrace.py request-tracing layer riding the same span machinery):
  * req_trace is a non-empty string (the request's 32-hex trace id)
  * req_span is a positive int (the span's own id within the request)
  * req_parent, when present, is a positive int (cross-process lineage —
    containment is NOT checked for it: the parent lives in another
    process's file and clock)
  * "cause", when present, is a non-empty string (route_attempt#n /
    exemplar-promotion classification)

Usable as a library (`validate_trace(path_or_dict)` returns the event
count, raises TraceFormatError) or a CLI (`python tools/validate_trace.py
trace.json ...` exits non-zero on the first invalid file).
"""
from __future__ import annotations

import json
import sys

__all__ = ["TraceFormatError", "validate_trace"]

_PHASES = {"X", "i", "C", "M"}
_INSTANT_SCOPES = {"p", "t", "g"}


class TraceFormatError(ValueError):
    """A trace event violates the chrome Trace Event Format subset."""


def _fail(i, ev, why):
    raise TraceFormatError(f"event[{i}] {why}: {json.dumps(ev)[:200]}")


def _check_event(i, ev):
    if not isinstance(ev, dict):
        raise TraceFormatError(f"event[{i}] is not an object: {ev!r}")
    name = ev.get("name")
    if not isinstance(name, str) or not name:
        _fail(i, ev, "missing/empty name")
    ph = ev.get("ph")
    if ph not in _PHASES:
        _fail(i, ev, f"bad phase {ph!r} (allowed: {sorted(_PHASES)})")
    ts = ev.get("ts")
    if not isinstance(ts, (int, float)) or isinstance(ts, bool) or ts < 0:
        _fail(i, ev, f"bad ts {ts!r}")
    for key in ("pid", "tid"):
        if key in ev and not isinstance(ev[key], (int, str)):
            _fail(i, ev, f"bad {key} {ev[key]!r}")
    if ph == "X":
        dur = ev.get("dur")
        if not isinstance(dur, (int, float)) or isinstance(dur, bool) \
                or dur < 0:
            _fail(i, ev, f"X event needs numeric dur, got {dur!r}")
    elif ph == "i":
        if "dur" in ev:
            _fail(i, ev, "instant event must not carry dur")
        if "s" in ev and ev["s"] not in _INSTANT_SCOPES:
            _fail(i, ev, f"bad instant scope {ev['s']!r}")
    elif ph == "C":
        args = ev.get("args")
        if not isinstance(args, dict) or not args:
            _fail(i, ev, "counter event needs non-empty args")
        for k, v in args.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                _fail(i, ev, f"counter args[{k!r}] not numeric: {v!r}")


# float µs arithmetic (ms -> µs conversions, clock-offset shifting in
# trace_merge) can nudge interval endpoints by sub-µs amounts
_SPAN_TOL_US = 5.0
_CLOCK_SYNC_ARGS = ("offset_us", "rtt_us", "perf_anchor_us",
                    "wall_anchor_us")
_REMOTE_PROFILE_INTS = ("rank", "request_id", "steps", "segments")


def _check_remote_profile(i, ev):
    args = ev.get("args")
    if not isinstance(args, dict):
        _fail(i, ev, "remote_profile event needs args")
    for k in _REMOTE_PROFILE_INTS:
        v = args.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            _fail(i, ev, f"remote_profile args[{k!r}] not a non-negative "
                         f"int: {v!r}")
    if args["request_id"] <= 0:
        _fail(i, ev, f"remote_profile request_id must be positive: "
                     f"{args['request_id']!r}")


def _check_request_span(i, ev, args):
    """X events stamped by serve/reqtrace.py: request-scoped lineage
    rides req_trace/req_span/req_parent args (see module docstring)."""
    rt = args.get("req_trace")
    if not isinstance(rt, str) or not rt:
        _fail(i, ev, f"bad req_trace {rt!r}")
    rs = args.get("req_span")
    if not isinstance(rs, int) or isinstance(rs, bool) or rs <= 0:
        _fail(i, ev, f"bad req_span {rs!r}")
    rp = args.get("req_parent")
    if rp is not None and (not isinstance(rp, int) or isinstance(rp, bool)
                           or rp <= 0):
        _fail(i, ev, f"bad req_parent {rp!r}")
    cause = args.get("cause")
    if cause is not None and (not isinstance(cause, str) or not cause):
        _fail(i, ev, f"bad cause {cause!r}")


def _check_spans(events):
    """Nested-span well-formedness across the whole (possibly merged,
    multi-process) event list; see the module docstring."""
    spans = {}      # (pid, trace, span_id) -> (ts, ts_end)
    children = []
    for i, ev in enumerate(events):
        if ev.get("ph") == "M" and ev.get("name") == "remote_profile":
            _check_remote_profile(i, ev)
            continue
        if ev.get("ph") == "M" and ev.get("name") == "clock_sync":
            args = ev.get("args")
            if not isinstance(args, dict):
                _fail(i, ev, "clock_sync event needs args")
            for k in _CLOCK_SYNC_ARGS:
                v = args.get(k)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    _fail(i, ev, f"clock_sync args[{k!r}] not numeric: {v!r}")
            continue
        if ev.get("ph") != "X":
            continue
        args = ev.get("args")
        if not isinstance(args, dict) or "span_id" not in args:
            continue
        sid = args["span_id"]
        if not isinstance(sid, int) or isinstance(sid, bool) or sid <= 0:
            _fail(i, ev, f"bad span_id {sid!r}")
        if "req_trace" in args:
            _check_request_span(i, ev, args)
        trace = args.get("trace")
        if trace is not None and not isinstance(trace, str):
            _fail(i, ev, f"bad trace id {trace!r}")
        key = (ev.get("pid"), trace, sid)
        if key in spans:
            _fail(i, ev, f"duplicate span_id {sid} in scope {key[:2]!r}")
        spans[key] = (ev["ts"], ev["ts"] + ev["dur"])
        parent = args.get("parent")
        if parent is not None:
            if not isinstance(parent, int) or isinstance(parent, bool) \
                    or parent <= 0:
                _fail(i, ev, f"bad parent {parent!r}")
            children.append((i, ev, key, (key[0], key[1], parent)))
    for i, ev, ckey, pkey in children:
        if pkey not in spans:
            continue        # parent in an earlier rolling segment
        cts, cend = spans[ckey]
        pts, pend = spans[pkey]
        if cts + _SPAN_TOL_US < pts or cend - _SPAN_TOL_US > pend:
            _fail(i, ev, f"span {ckey[2]} [{cts},{cend}] escapes parent "
                         f"{pkey[2]} [{pts},{pend}]")


def validate_trace(trace):
    """Validate a chrome trace; `trace` is a file path, a JSON string, or
    an already-parsed dict. Returns the number of events checked."""
    if isinstance(trace, str):
        if trace.lstrip().startswith(("{", "[")):
            trace = json.loads(trace)
        else:
            with open(trace) as f:
                trace = json.load(f)
    if isinstance(trace, list):      # bare event-array form is also legal
        events = trace
    elif isinstance(trace, dict):
        events = trace.get("traceEvents")
        if not isinstance(events, list):
            raise TraceFormatError("top level has no traceEvents list")
    else:
        raise TraceFormatError(f"trace is not an object: {type(trace)}")
    for i, ev in enumerate(events):
        _check_event(i, ev)
    _check_spans(events)
    return len(events)


def main(argv):
    if not argv:
        print("usage: validate_trace.py TRACE.json [...]", file=sys.stderr)
        return 2
    for path in argv:
        try:
            n = validate_trace(path)
        except (TraceFormatError, OSError, json.JSONDecodeError) as e:
            print(f"{path}: INVALID: {e}", file=sys.stderr)
            return 1
        print(f"{path}: ok ({n} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
