#!/usr/bin/env python
"""Pack an image folder/list into RecordIO (reference tools/im2rec.py:
--list generation + multi-worker packing over OpenCV; here PIL + the
native C++ record codec).

Usage:
    # 1) make a list file (label from folder structure)
    python tools/im2rec.py --list data/train data/imgs
    # 2) pack it
    python tools/im2rec.py data/train data/imgs --quality 95
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix, root, recursive=True, train_ratio=1.0, shuffle=True,
              chunks=1):
    """Write prefix.lst lines: <index>\t<label>\t<relpath> (reference
    im2rec.py make_list)."""
    images = []
    classes = {}
    if recursive:
        for dirpath, _, files in sorted(os.walk(root)):
            rel = os.path.relpath(dirpath, root)
            for fn in sorted(files):
                if fn.lower().endswith(EXTS):
                    if rel not in classes:
                        classes[rel] = len(classes)
                    images.append((os.path.join(rel, fn), classes[rel]))
    else:
        for fn in sorted(os.listdir(root)):
            if fn.lower().endswith(EXTS):
                images.append((fn, 0))
    if shuffle:
        random.seed(100)
        random.shuffle(images)
    n_train = int(len(images) * train_ratio)
    splits = [("", images[:n_train])]
    if train_ratio < 1.0:
        splits = [("_train", images[:n_train]), ("_val", images[n_train:])]
    for suffix, imgs in splits:
        with open(f"{prefix}{suffix}.lst", "w") as f:
            for i, (path, label) in enumerate(imgs):
                f.write(f"{i}\t{label}\t{path}\n")
    return classes


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(prefix, root, quality=95, resize=0, color=1):
    from incubator_mxnet_tpu import recordio
    from incubator_mxnet_tpu.image.image import imread, imencode, resize_short

    lst = prefix + ".lst"
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, relpath in read_list(lst):
        full = os.path.join(root, relpath)
        try:
            img = imread(full, to_rgb=color)
        except Exception as e:
            print(f"[im2rec] skip {relpath}: {e}", file=sys.stderr)
            continue
        if resize:
            img = resize_short(img, resize)
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        payload = recordio.pack(header, imencode(img, quality=quality))
        rec.write_idx(idx, payload)
        count += 1
    rec.close()
    print(f"[im2rec] packed {count} images into {prefix}.rec")
    return count


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="prefix of .lst/.rec/.idx files")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate the .lst file instead of packing")
    ap.add_argument("--no-recursive", dest="recursive",
                    action="store_false", default=True,
                    help="flat listing with label 0 (no class subfolders)")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--color", type=int, default=1)
    args = ap.parse_args()
    if args.list:
        classes = make_list(args.prefix, args.root, args.recursive,
                            args.train_ratio, not args.no_shuffle)
        print(f"[im2rec] wrote {args.prefix}.lst ({len(classes)} classes)")
        return 0
    pack(args.prefix, args.root, args.quality, args.resize, args.color)
    return 0


if __name__ == "__main__":
    sys.exit(main())
