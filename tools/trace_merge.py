#!/usr/bin/env python
"""Merge per-process profiler traces into one wall-clock timeline.

Each training/serving process dumps its own chrome trace (plus rolling
segments) with timestamps on its private ``time.perf_counter()`` base —
two files from two workers cannot be eyeballed side by side, and a
straggler hunt needs exactly that. This tool merges N such files into a
single Perfetto-loadable timeline:

  1. Per input file, pick the best ``clock_sync`` metadata sample: the
     smallest-RTT peer sample when the process heartbeated a server
     (kvstore _hb_loop records offset = server_time - midpoint(t0, t1),
     the classic NTP estimate), else the ``peer: "self"`` anchor the
     profiler writes at dump time.
  2. Shift every event:  ts' = ts - perf_anchor + wall_anchor + offset —
     first onto the process's wall clock, then onto the server's.
  3. Assign each input file a distinct pid (with a ``process_name``
     metadata event naming the source file + trace id), normalize the
     origin to the earliest event, sort, and emit one trace.

Span linkage (worker pushpull span ids carried on the kvstore wire into
server handler span args) survives the merge untouched, so a server
``server:push`` span can be matched to the worker span that caused it by
``args.link_span`` + ``args.link_trace``.

Request traces (serve/reqtrace.py) join the same way: spans carrying
``args.req_trace`` keep their request ids through the merge, each input
file's process_name label lists the request trace ids it contains
(``req[...]``), so one request can be followed router -> prefill ->
decode across the per-process tracks by filtering on its req_trace.

CLI:
  python tools/trace_merge.py -o merged.json worker0.json worker1.json ...

Library:
  merge_traces([path, ...]) -> {"traceEvents": [...], ...}
"""
from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["MergeError", "best_clock_sync", "merge_traces"]


class MergeError(ValueError):
    """Input trace cannot be placed on the shared timeline."""


def _load_events(trace):
    """Events of one input: a file path, an already-parsed trace dict,
    or a JSON string (fleetobs remote-profile payloads fetched over the
    kvstore wire merge without a temp-file round trip)."""
    label = "<trace>"
    if isinstance(trace, str):
        if trace.lstrip().startswith(("{", "[")):
            trace = json.loads(trace)
        else:
            label = trace
            with open(trace) as f:
                trace = json.load(f)
    if isinstance(trace, list):
        return trace, label
    events = trace.get("traceEvents") if isinstance(trace, dict) else None
    if not isinstance(events, list):
        raise MergeError(f"{label}: top level has no traceEvents list")
    return events, label


def best_clock_sync(events):
    """The clock_sync sample to align this process with: smallest RTT
    among peer samples (a measured offset to the server's clock beats any
    self anchor), else the self anchor (offset 0 to its own wall clock).
    Returns the args dict, or None when the trace carries no sample."""
    peer_best = self_best = None
    for ev in events:
        if ev.get("ph") != "M" or ev.get("name") != "clock_sync":
            continue
        args = ev.get("args") or {}
        if not all(isinstance(args.get(k), (int, float))
                   for k in ("offset_us", "rtt_us", "perf_anchor_us",
                             "wall_anchor_us")):
            continue
        if args.get("peer") == "self":
            self_best = args
        elif peer_best is None or args["rtt_us"] < peer_best["rtt_us"]:
            peer_best = args
    return peer_best or self_best


def merge_traces(paths, allow_unsynced=False):
    """Merge per-process traces (file paths, parsed dicts, or JSON
    strings) into one timeline dict. Raises MergeError when an input
    has no clock_sync anchor (pass allow_unsynced=True to keep it on
    its raw timebase, origin-aligned only)."""
    merged = []
    for pid, path in enumerate(paths):
        events, label = _load_events(path)
        sync = best_clock_sync(events)
        if sync is None and not allow_unsynced:
            raise MergeError(
                f"{label}: no clock_sync sample; run with "
                "MXNET_STEP_ATTRIBUTION=1 so dumps carry a clock anchor, "
                "or pass --allow-unsynced")
        shift = 0.0
        if sync is not None:
            shift = (sync["wall_anchor_us"] - sync["perf_anchor_us"]
                     + sync["offset_us"])
        trace_ids = set()
        req_traces = set()
        for ev in events:
            e = dict(ev)
            e["pid"] = pid
            if isinstance(e.get("ts"), (int, float)):
                e["ts"] = e["ts"] + shift
            a = e.get("args") if isinstance(e.get("args"), dict) else {}
            t = a.get("trace")
            if isinstance(t, str):
                trace_ids.add(t)
            rt = a.get("req_trace")
            if isinstance(rt, str):
                req_traces.add(rt)
            merged.append(e)
        rp = next((ev for ev in events if ev.get("ph") == "M"
                   and ev.get("name") == "remote_profile"
                   and isinstance(ev.get("args"), dict)), None)
        if label != "<trace>":
            label = os.path.basename(label)
        elif rp is not None:
            label = f"remote_profile:rank{rp['args'].get('rank')}"
        else:
            label = f"trace{pid}"
        if trace_ids:
            label += f" [{', '.join(sorted(trace_ids))}]"
        if req_traces:
            # request ids this process participated in (reqtrace layer);
            # truncated to keep Perfetto's process rail readable
            shown = sorted(req_traces)[:4]
            more = len(req_traces) - len(shown)
            label += " req[" + ", ".join(t[:8] for t in shown)
            label += (f", +{more}" if more > 0 else "") + "]"
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "ts": 0, "cat": "__metadata",
                       "args": {"name": label}})
    # one shared origin: earliest REAL event (metadata rows sit at ts 0
    # by convention and must not drag the origin around)
    real = [e["ts"] for e in merged
            if e.get("ph") != "M" and isinstance(e.get("ts"), (int, float))]
    origin = min(real) if real else 0.0
    for e in merged:
        if e.get("ph") == "M":
            e["ts"] = 0
        elif isinstance(e.get("ts"), (int, float)):
            e["ts"] = max(0.0, e["ts"] - origin)
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def main(argv):
    ap = argparse.ArgumentParser(
        description="merge per-process profiler traces onto one "
                    "wall-clock timeline")
    ap.add_argument("traces", nargs="+", help="per-process trace JSONs")
    ap.add_argument("-o", "--output", default="merged_trace.json")
    ap.add_argument("--allow-unsynced", action="store_true",
                    help="keep files without a clock_sync anchor on "
                         "their raw timebase instead of failing")
    args = ap.parse_args(argv)
    try:
        merged = merge_traces(args.traces,
                              allow_unsynced=args.allow_unsynced)
    except (MergeError, OSError, json.JSONDecodeError) as e:
        print(f"trace_merge: {e}", file=sys.stderr)
        return 1
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from validate_trace import validate_trace
    validate_trace(merged)      # never emit a timeline Perfetto drops
    with open(args.output, "w") as f:
        json.dump(merged, f)
    print(f"{args.output}: {len(merged['traceEvents'])} events from "
          f"{len(args.traces)} processes")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
