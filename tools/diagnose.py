#!/usr/bin/env python
"""Environment diagnostics (reference tools/diagnose.py: python/platform/
library versions, build flags, network checks for the PS cluster).

TPU edition: jax/device/mesh facts replace the CUDA and ps-lite sections."""
from __future__ import annotations

import os
import platform
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.machine(), platform.architecture()[0])

    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("release      :", platform.release())

    print("----------Framework Info----------")
    try:
        import incubator_mxnet_tpu as mx
        print("incubator_mxnet_tpu:", mx.__version__,
              "at", os.path.dirname(mx.__file__))
        from incubator_mxnet_tpu import runtime
        feats = runtime.feature_list()
        on = [f.name for f in feats if f.enabled]
        print("Features     :", ", ".join(on) if on else "(none)")
    except Exception as e:
        print("incubator_mxnet_tpu import FAILED:", e)

    print("----------JAX / Device Info----------")
    try:
        import jax
        import jaxlib
        print("jax          :", jax.__version__)
        print("jaxlib       :", jaxlib.__version__)
        devs = jax.devices()
        print("device count :", len(devs))
        for d in devs[:8]:
            print(f"  [{d.id}] {d.device_kind} ({d.platform})")
        print("process      :", jax.process_index(), "/", jax.process_count())
    except Exception as e:
        print("jax probe FAILED:", e)

    print("----------Environment----------")
    for k in sorted(os.environ):
        if k.startswith(("MXTPU_", "MXNET_", "JAX_", "XLA_", "DMLC_", "TPU_")):
            print(f"{k}={os.environ[k]}")

    print("----------Declared Env Vars (util.ENV_VARS)----------")
    try:
        from incubator_mxnet_tpu.util import ENV_VARS
        width = max(len(n) for n in ENV_VARS)
        for name, spec in ENV_VARS.items():
            live = os.environ.get(name)
            live = "(unset)" if live is None else f"={live}"
            print(f"{name:<{width}} {spec.kind:<4} "
                  f"default={spec.default!r} {live}")
            print(f"{'':<{width}}      {spec.doc}")
    except Exception as e:
        print("ENV_VARS table FAILED:", e)

    print("----------Executable Cache (compile_cache)----------")
    try:
        from incubator_mxnet_tpu import compile_cache
        ds = compile_cache.disk_stats()
        if ds["dir"] is None:
            print("disk tier    : disabled (MXNET_EXEC_CACHE_DIR unset)")
        else:
            budget = ds["budget"]
            pct = (f" ({100.0 * ds['bytes'] / budget:.1f}% of "
                   f"{budget} budget)") if budget > 0 else " (unbounded)"
            print("dir          :", ds["dir"])
            print("entries      :", ds["entries"])
            print(f"occupancy    : {ds['bytes']} bytes{pct}")
        s = compile_cache.stats()
        print("mem entries  :", s["mem_entries"])
        print("counters     :",
              {k: s[k] for k in ("hits", "misses", "disk_hits",
                                 "evictions", "disk_errors", "fallbacks")})
    except Exception as e:
        print("compile_cache probe FAILED:", e)

    print("----------Kernel Autotuner (tune)----------")
    try:
        from incubator_mxnet_tpu import tune
        # importing the kernel providers registers their search spaces so
        # winners() can decode what the persistent store holds
        from incubator_mxnet_tpu.parallel import conv_backward  # noqa: F401
        from incubator_mxnet_tpu.parallel import fused_conv  # noqa: F401
        s = tune.stats()
        print("counters     :",
              {k: s[k] for k in ("searches", "hits", "disk_hits",
                                 "disk_errors", "fallbacks")})
        recs = tune.winners()
        if not recs:
            print("winners      : (none recorded)")
        else:
            by_dev = {}
            for rec in recs.values():
                by_dev.setdefault(rec.get("device_kind", "?"), []).append(rec)
            for dev in sorted(by_dev):
                group = by_dev[dev]
                print(f"device kind  : {dev} ({len(group)} tuned shapes)")
                for rec in sorted(group, key=lambda r: (r["kernel"],
                                                        r["key"])):
                    t = rec.get("timings_us", {})
                    best = t.get(rec["winner"])
                    best = "" if best is None else f" {best}us"
                    print(f"  {rec['kernel']:<16} -> {rec['winner']}{best}"
                          f"  [{rec['key']}]")
    except Exception as e:
        print("tune probe FAILED:", e)

    print("----------Fault Tolerance (fault)----------")
    try:
        from incubator_mxnet_tpu import fault
        s = fault.stats()
        print("checkpoint   :",
              {k.replace("ckpt_", ""): s[k] for k in
               ("ckpt_saves", "ckpt_async_snapshots", "ckpt_dropped",
                "ckpt_errors", "ckpt_fallbacks", "ckpt_last_step")})
        print("write ms     :", round(s["ckpt_write_ms"], 1))
        print("liveness     :",
              {k: s[k] for k in ("heartbeats_sent", "dead_nodes_seen",
                                 "stragglers_seen", "rejoins",
                                 "membership_changes")})
        print("injected     :", s["faults_injected"])
        print("dead nodes   :", fault.get_dead_nodes())
    except Exception as e:
        print("fault probe FAILED:", e)

    print("----------Step Breakdown (profiler attribution)----------")
    try:
        from incubator_mxnet_tpu import profiler
        ps = profiler.phase_stats()
        print("attribution  :", "on" if profiler.attribution_enabled()
              else "off (MXNET_STEP_ATTRIBUTION unset)")
        print("steps closed :", ps["steps"], " spans:", ps["spans"])
        for phase in sorted(ps["phases"],
                            key=lambda p: -ps["phases"][p]["total_ms"]):
            row = ps["phases"][phase]
            print(f"  {phase:<14} {row['count']:>7}x "
                  f"avg {row['avg_ms']:8.3f}ms "
                  f"max {row['max_ms']:8.3f}ms")
        costs = profiler.cost_stats()
        if costs:
            print("compiler cost:")
            for key in sorted(costs):
                row = costs[key]
                gf = row.get("flops")
                inten = row.get("intensity")
                print(f"  {key:<28} "
                      + (f"{gf / 1e9:9.3f} GFLOP" if gf else "   (no flops)")
                      + (f"  {inten:8.2f} F/B" if inten else ""))
        mfu = profiler.mfu_stats()
        if mfu:
            print(f"MFU          : {mfu['mfu'] * 100:.1f}% "
                  f"({mfu['key']}, compiler cost / compute phase)")
        from incubator_mxnet_tpu import fault as _flt
        print("flight rec   :", "on -> " + os.environ.get(
            "MXNET_FLIGHT_RECORDER", "") if _flt.flight_enabled()
            else "off (MXNET_FLIGHT_RECORDER unset)")
    except Exception as e:
        print("step breakdown probe FAILED:", e)

    print("----------Fleet Observability (fleetobs)----------")
    try:
        from incubator_mxnet_tpu import fleetobs
        print("plane        :", "on" if fleetobs.enabled()
              else "off (MXNET_FLEET_OBS unset)")
        s = fleetobs.stats()
        print("snapshots    :",
              {k.replace("snapshots_", ""): s[k] for k in
               ("snapshots_built", "snapshots_skipped",
                "snapshots_folded")})
        print("slo engine   :",
              {k: s[k] for k in ("slo_evals", "alerts_raised",
                                 "alerts_resolved")})
        print("profiling    :",
              {k.replace("profile_", ""): s[k] for k in
               ("profile_requests", "profile_runs", "profile_pushes",
                "profile_fetches", "profile_bytes")})
        regs = fleetobs.registries()
        if not regs:
            print("registries   : (none live in this process)")
        for reg in regs:
            occ = reg.occupancy()
            print("registry     :",
                  {k: occ[k] for k in ("ranks", "phases",
                                       "pending_commands",
                                       "stored_profiles",
                                       "alerts_active")})
            for alert in reg.engine.active():
                print(f"  ALERT {alert['spec']} value={alert['value']} "
                      f"burn={alert['burn_short']}/{alert['burn_long']}")
            lf = occ["last_fetch"]
            if lf:
                print(f"  last fetch : rank {lf['rank']} gen {lf['gen']} "
                      f"req {lf['request_id']}")
    except Exception as e:
        print("fleetobs probe FAILED:", e)

    print("----------Control Plane (serve)----------")
    try:
        from incubator_mxnet_tpu.serve import control_plane
        from incubator_mxnet_tpu.util import getenv_int
        s = control_plane.stats()
        print("registry     :",
              {k: s[k] for k in ("registrations", "deregistrations",
                                 "beats", "graceful_shutdowns")})
        print("rollout      :",
              {k.replace("rollout_", ""): s[k] for k in
               ("rollouts_started", "rollout_waves",
                "rollout_replicas_updated", "rollout_replica_failures",
                "rollbacks")})
        print("router knobs :",
              {"deadline_ms": getenv_int("MXNET_ROUTER_DEADLINE_MS"),
               "retries": getenv_int("MXNET_ROUTER_RETRIES"),
               "hedge_delay_ms": getenv_int("MXNET_ROUTER_HEDGE_DELAY_MS"),
               "breaker_failures":
                   getenv_int("MXNET_ROUTER_BREAKER_FAILURES"),
               "breaker_cooldown_ms":
                   getenv_int("MXNET_ROUTER_BREAKER_COOLDOWN_MS")})
        print("live window  :", control_plane._live_window_s(), "s")
    except Exception as e:
        print("control plane probe FAILED:", e)

    print("----------Disaggregated Serving----------")
    try:
        from incubator_mxnet_tpu.serve import disagg
        from incubator_mxnet_tpu.util import (getenv_bool, getenv_int,
                                              getenv_str)
        print("roles        :",
              {"role": getenv_str("MXNET_DISAGG_ROLE"),
               "prefill_chunk":
                   getenv_int("MXNET_DISAGG_PREFILL_CHUNK"),
               "ship_ttl_s": getenv_int("MXNET_DISAGG_SHIP_TTL")})
        print("prefix cache :",
              {"enabled": getenv_bool("MXNET_PREFIX_CACHE"),
               "max_pages": getenv_int("MXNET_PREFIX_CACHE_PAGES")})
        s = disagg.stats()
        print("shipping     :",
              {k: s.get(k, 0) for k in ("prefill_requests", "chunks_total",
                                        "pages_shipped", "bytes_shipped",
                                        "pages_fetched", "fetch_misses")})
        # in-process probe: a tiny radix cache over a throwaway
        # allocator — exercises share/CoW/evict without any device work
        from incubator_mxnet_tpu.serve.decode import PageAllocator
        from incubator_mxnet_tpu.serve.prefix_cache import PrefixCache
        alloc = PageAllocator(8)
        cache = PrefixCache(alloc, 4, max_pages=4)
        seq = [1, 2, 3, 4, 5, 6]
        pages = alloc.alloc(2)
        cache.insert(seq, pages, len(seq))
        alloc.free(pages)
        hit_pages, covered, partial = cache.lookup(seq + [7])
        cache.lookup([9, 9, 9, 9, 9])       # miss
        cs = cache.stats()
        print("probe        :",
              {"covered": covered, "partial": partial,
               "hit_rate": cs["hit_rate"],
               "cached_pages": cs["cached_pages"]})
        alloc.free(hit_pages)
        cache.clear()
        ok = alloc.free_count == 8
        print("probe drain  :", "refcounts returned to 0" if ok
              else f"LEAKED pages ({alloc.free_count}/8 free)")
    except Exception as e:
        print("disagg probe FAILED:", e)

    print("----------Speculative Decoding----------")
    try:
        from incubator_mxnet_tpu.util import getenv_bool, getenv_int
        print("knobs        :",
              {"enabled": getenv_bool("MXNET_SPEC_DECODE"),
               "k": getenv_int("MXNET_SPEC_K"),
               "adapt": getenv_bool("MXNET_SPEC_ADAPT"),
               "accept_floor_pct":
                   getenv_int("MXNET_SPEC_ACCEPT_FLOOR_PCT")})
        print("router SLO   :",
              {"split": getenv_bool("MXNET_ROUTER_SLO_SPLIT"),
               "ttft_slo_ms": getenv_int("MXNET_ROUTER_TTFT_SLO_MS"),
               "token_slo_ms": getenv_int("MXNET_ROUTER_TOKEN_SLO_MS")})
        # in-process probe: the numpy self-draft + adaptive-k policy
        # over a throwaway toy predictor — no device work, no compiles
        from incubator_mxnet_tpu.serve.decode import DecodePredictor
        from incubator_mxnet_tpu.serve.spec_decode import SpecDecoder
        pred = DecodePredictor.toy(slots=2, page_size=4, num_pages=16,
                                   max_pages_per_seq=4,
                                   prompt_buckets=(4,))
        spec = SpecDecoder(pred, k=4)
        draft = spec.make_draft([1, 2, 3])
        drafted = draft.propose(4, 3)
        draft.sync(3, [4] + drafted[:1])        # reject 2 of 3
        print("probe        :",
              {"verify_key": spec._verify_key(),
               "drafted": len(drafted), "rows_after_sync": draft.rows,
               "k_walk": [spec.next_k(4, 0.2), spec.next_k(2, 0.95),
                          spec.next_k(3, 0.7)]})
        ok = draft.rows == 5
        print("probe sync   :", "rollback truncated to committed rows"
              if ok else f"WRONG row count ({draft.rows} != 5)")
    except Exception as e:
        print("spec decode probe FAILED:", e)

    print("----------Request Tracing----------")
    try:
        from incubator_mxnet_tpu.util import getenv_bool, getenv_int
        from incubator_mxnet_tpu.serve import reqtrace as _rt
        print("knobs        :",
              {"enabled": getenv_bool("MXNET_REQTRACE"),
               "sample_per_mille": getenv_int("MXNET_REQTRACE_SAMPLE"),
               "ring": getenv_int("MXNET_REQTRACE_RING")})
        # in-process probe: force the gate on, walk one synthetic request
        # through mint -> header roundtrip -> span -> finish, then reset
        # so the probe leaves no records behind
        _rt.enable(True)
        try:
            ctx = _rt.mint(deadline_ms=250.0)
            back = _rt.from_header(_rt.to_header(ctx))
            with _rt.activate(ctx):
                with _rt.span("router_queue"):
                    pass
            _rt.finish(ctx, status="error", cause="diagnose-probe",
                       ttft_ms=123.0, total_ms=130.0)
            snap = _rt.ring_snapshot()
            print("probe        :",
                  {"header_ok": back is not None
                   and back.trace_id == ctx.trace_id,
                   "records": _rt.record_count(),
                   "ring": {"recent": len(snap["recent"]),
                            "exemplars": len(snap["exemplars"]),
                            "capacity": snap["capacity"]}})
            print("slowest-5    :",
                  [(r["trace"][:8],
                    r.get("total_ms") or r.get("ttft_ms")
                    or r.get("elapsed_ms"))
                   for r in _rt.slowest(5)])
        finally:
            _rt.reset()
    except Exception as e:
        print("request tracing probe FAILED:", e)

    print("----------Composed Parallelism (pipeline schedules)----------")
    try:
        from incubator_mxnet_tpu.parallel.pipeline import (REMAT_MODES,
                                                           SCHEDULES,
                                                           schedule_stats)
        from incubator_mxnet_tpu.util import getenv_bool, getenv_int, \
            getenv_str
        from incubator_mxnet_tpu import profiler as _prof
        print("schedule     :", getenv_str("MXTPU_PP_SCHEDULE"),
              f"(MXTPU_PP_SCHEDULE; one of {'/'.join(SCHEDULES)})")
        print("remat        :", getenv_str("MXNET_REMAT"),
              f"(MXNET_REMAT; one of {'/'.join(REMAT_MODES)})")
        print("vstages      :", getenv_int("MXTPU_PP_VSTAGES"),
              "(MXTPU_PP_VSTAGES; interleaved chunks per rank)")
        print("offload      :", getenv_bool("MXNET_PP_OFFLOAD"),
              "(MXNET_PP_OFFLOAD; stage inputs -> pinned host)")
        print("bubble fraction by (stages, microbatches):")
        print("   S  M   gpipe   1f1b  il(v2)    zb1   "
              "live/stage(gpipe -> 1f1b)")
        for s, m in ((2, 4), (4, 8), (4, 16), (8, 32)):
            g = schedule_stats("gpipe", s, m)
            f = schedule_stats("1f1b", s, m)
            il = schedule_stats("interleaved", s, m, n_chunks=2)
            z = schedule_stats("zb1", s, m)
            print(f"  {s:2d} {m:2d}  {g['bubble_fraction']:.4f} "
                  f"{f['bubble_fraction']:.4f}  {il['bubble_fraction']:.4f} "
                  f"{z['bubble_fraction']:.4f}   "
                  f"{g['max_live_per_stage']} -> {f['max_live_per_stage']}")
        phases = _prof.last_step_phases()
        if phases.get("pp_bubble") is not None:
            print("last step    :", {k: round(v, 2)
                                     for k, v in sorted(phases.items())})
        else:
            print("last step    : no attributed pp_bubble phase recorded "
                  "(run a pp>1 step with attribution on)")
    except Exception as e:
        print("composed parallelism probe FAILED:", e)

    print("----------Static Analysis (mxlint)----------")
    try:
        from tools.mxlint import lint_paths
        pkg = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "incubator_mxnet_tpu")
        res = lint_paths([pkg])
        summary = res.as_dict()
        print("files scanned:", summary["files_scanned"])
        print("findings     :", len(summary["findings"]),
              summary["counts"] if summary["counts"] else "")
        print("suppressed   :", len(summary["suppressed"]))
        for s in summary["suppressed"]:
            print(f"  {s['rule']} {s['path']}:{s['line']} ({s['reason']})")
        for f in summary["findings"][:20]:
            print(f"  {f['rule']} {f['path']}:{f['line']} {f['message']}")
    except Exception as e:
        print("mxlint probe FAILED:", e)

    print("----------Concurrency Sanitizer (mxsan)----------")
    try:
        from incubator_mxnet_tpu import mxsan as _mx
        from incubator_mxnet_tpu.util import getenv_int, getenv_str
        from tools.mxsan import RULES as SAN_RULES
        from tools.mxsan import analyze, declared_edge_count
        from tools.mxsan.waivers import WAIVERS as SAN_WAIVERS
        from tools.mxlint.lock_order import (BLOCKING_OK,
                                             CROSS_MODULE_EDGES,
                                             LOCK_ORDER)
        print("gate         :", "on" if _mx.enabled()
              else "off (MXNET_MXSAN unset)")
        print("knobs        :",
              {"ring": getenv_int("MXNET_MXSAN_RING"),
               "log": getenv_str("MXNET_MXSAN_LOG") or "(unset)"})
        print("declared     :",
              {"modules": len(LOCK_ORDER),
               "edges": declared_edge_count(),
               "cross_module": len(CROSS_MODULE_EDGES),
               "blocking_ok": len(BLOCKING_OK)})
        print("rules        :")
        for rule, (title, _hint) in sorted(SAN_RULES.items()):
            print(f"  {rule}: {title}")
        # in-process probe: force the gate on, nest two probe locks in
        # profiler.py's declared order, and replay the witness through
        # the analyzer — a clean run proves the loop end to end; the
        # finally leaves no witness state behind
        _mx.enable(True)
        try:
            outer = _mx.lock("profiler.py", "_lock")
            inner = _mx.lock("profiler.py", "_clock")
            with outer:
                with inner:
                    pass
            wit = _mx.witness()
            res = analyze(wit, waivers=())
            print("probe        :",
                  {"records": _mx.record_count(),
                   "edges": [f"{e['a']} -> {e['b']}"
                             for e in wit["edges"]],
                   "findings": [f.key for f in res.findings] or "clean"})
        finally:
            _mx.reset()
        print("waivers      :", len(SAN_WAIVERS))
        for rule, glob, reason in SAN_WAIVERS:
            print(f"  {rule} on {glob}: {reason}")
        print("run it       : MXNET_MXSAN=1 MXNET_MXSAN_LOG=w.json "
              "<workload>; python -m tools.mxsan w.json [--format=json]")
    except Exception as e:
        print("mxsan probe FAILED:", e)

    print("----------Graph Analysis (shardlint)----------")
    try:
        from incubator_mxnet_tpu import shardlint
        from tools.shardlint import RULES
        from tools.shardlint.corpus import entries
        from tools.shardlint.waivers import WAIVERS
        s = shardlint.stats()
        print("capture      :", "on" if s["enabled"] else
              "off (MXNET_SHARDLINT unset)")
        print("counters     :",
              {k: s[k] for k in ("captures", "jit", "tuned",
                                 "partition", "dropped")})
        print("rules        :")
        for rule, (title, _hint) in sorted(RULES.items()):
            print(f"  {rule}: {title}")
        print("corpus       :", ", ".join(entries()))
        print("waivers      :", len(WAIVERS))
        for rule, glob, reason in WAIVERS:
            print(f"  {rule} on {glob}: {reason}")
        print("run it       : python -m tools.shardlint [--format=json]")
    except Exception as e:
        print("shardlint probe FAILED:", e)
    return 0


if __name__ == "__main__":
    sys.exit(main())
