"""mxlint — static trace-safety / concurrency / env-hygiene checks for
incubator_mxnet_tpu.

Run it:

    python -m tools.mxlint [paths...] [--format=text|json] [--changed]

or programmatically:

    from tools.mxlint import lint_paths
    result = lint_paths(["incubator_mxnet_tpu"])

Pure stdlib (``ast`` + ``os`` + ``json``); never imports the package it
lints, so it runs in milliseconds with no jax initialization.
"""
from __future__ import annotations

import ast
import os

from .core import RULES, Finding, ModuleInfo
from . import rules_trace, rules_concurrency, rules_env

__all__ = ["RULES", "Finding", "LintResult", "lint_paths", "lint_source"]

_SKIP_DIRS = {"__pycache__", "build", "dist", ".git", ".pytest_cache"}


class LintResult:
    """Findings + suppressions for one lint run."""

    def __init__(self):
        self.findings = []       # active Finding objects
        self.suppressed = []     # Finding objects silenced by a disable
        self.errors = []         # (path, message) for unparseable files
        self.files_scanned = 0

    @property
    def clean(self):
        return not self.findings and not self.errors

    def as_dict(self):
        counts = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "reason": f.suppress_reason}
                for f in self.suppressed],
            "errors": [{"path": p, "message": m} for p, m in self.errors],
            "counts": counts,
        }


def _iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.endswith(".egg-info"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _package_root(paths):
    """Directory containing util.py, for registry extraction: the first
    path that is (or contains) the incubator_mxnet_tpu package."""
    for path in paths:
        path = os.path.abspath(path)
        cand = path if os.path.isdir(path) else os.path.dirname(path)
        while cand and cand != os.path.dirname(cand):
            if os.path.isfile(os.path.join(cand, "util.py")) and \
                    os.path.isfile(os.path.join(cand, "__init__.py")):
                return cand
            nested = os.path.join(cand, "incubator_mxnet_tpu")
            if os.path.isfile(os.path.join(nested, "util.py")):
                return nested
            cand = os.path.dirname(cand)
    return None


def lint_source(src, path="<string>", registry=None):
    """Lint one source string; returns (findings, suppressed)."""
    mod = ModuleInfo(path, src, relpath=path)
    return _apply_rules(mod, registry)


def _apply_rules(mod, registry):
    raw = []
    raw += rules_trace.check(mod)
    raw += rules_concurrency.check(mod)
    raw += rules_env.check(mod, registry=registry)
    findings, suppressed = [], []
    for f in raw:
        reason = mod.suppression_for(f.rule, f.line)
        if reason is not None:
            f.suppress_reason = reason
            suppressed.append(f)
        else:
            findings.append(f)
    return findings, suppressed


def lint_paths(paths, registry=None):
    """Lint files/directories. `registry` overrides the env-var registry
    normally parsed out of the package's util.py."""
    result = LintResult()
    if registry is None:
        root = _package_root(paths)
        if root is not None:
            registry = rules_env.load_registry(root)
    for path in _iter_py_files(paths):
        rel = os.path.relpath(path).replace("\\", "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            mod = ModuleInfo(path, src, relpath=rel)
        except (OSError, SyntaxError) as e:
            result.errors.append((rel, str(e)))
            continue
        result.files_scanned += 1
        findings, suppressed = _apply_rules(mod, registry)
        result.findings.extend(findings)
        result.suppressed.extend(suppressed)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
