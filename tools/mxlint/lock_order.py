"""Declared lock-acquisition order per module (CC02's ground truth).

The reference engine documented its mutex hierarchy in comments next to the
engine code; here it is a table the linter enforces.  Keys are paths
relative to the package root (``incubator_mxnet_tpu``); values are the
locks a module may hold, in the only order nesting is allowed.  Lock names
are the normalized dotted spelling at the acquisition site (``with
self._lock`` -> ``self._lock``).

A lock acquired in a covered module but absent from its entry is an
*undeclared* lock (CC02): declare it here — stating where a new lock sits
in the hierarchy is the point of the exercise.

Modules not listed are uncovered: CC02 does not fire there (CC01/CC03
still do) — unless the module self-declares its hierarchy with a
top-level ``MXLINT_LOCK_ORDER = ("first", "second")`` tuple, which CC02
then enforces the same way.
"""

LOCK_ORDER = {
    # profiler: event/counter lock, compile-tracker clock, memory book,
    # and track_jit's per-wrapper first-call latch.
    # PR 3's GC deadlock came precisely from violating this file's order.
    "profiler.py": ("_lock", "_clock", "_mlock", "state_lock"),
    # compile_cache: per-wrapper single-flight compile lock outermost
    # (disk/LRU/counter updates nest under it), per-wrapper sig memo and
    # the module LRU+counter lock are leaves.
    "compile_cache.py": ("self._compile_lock", "self._lock", "_lock"),
    # tune: one module lock guards the winner table and counters; the
    # disk tier is written outside it (atomic tmp+rename, last wins).
    "tune.py": ("_lock",),
    # shardlint: one module lock guards the capture buffer, annotation
    # table, and counters; recorders never call out while holding it, so
    # it nests under nothing and nothing nests under it.
    "shardlint.py": ("_lock",),
    "serve/batcher.py": ("self._lock",),
    "serve/stats.py": ("self._lock",),
    # serve/control_plane: a ServeRegistry/ReplicaAgent/RolloutManager
    # instance lock guards its own table or wire client; the module
    # counter lock is a LEAF — _bump and flight_record run only after
    # instance state is settled (fleetobs discipline).
    "serve/control_plane.py": ("self._lock", "_lock"),
    # serve/router: the routing lock (replica table + breakers +
    # round-robin cursor) is OUTERMOST; RouterStats' counter lock is a
    # LEAF. Breaker transitions are recorded (stats/flight/log) only
    # after releasing the routing lock; network calls hold neither.
    "serve/router.py": ("self._rlock", "self._lock"),
    # serve/server: ModelServer's drain/swap lock serializes begin_drain
    # against reload's pause→quiesce→swap→resume; batcher/stats locks
    # are acquired by callees, not nested at this module's sites. The
    # ship-client lock (lazy kvstore client for KV-page shipping) is a
    # LEAF — it guards only client construction/teardown and never
    # nests with the drain lock.
    "serve/server.py": ("self._drain_lock", "self._ship_lock"),
    # serve/prefix_cache: one cache lock guards the radix tree, LRU
    # clock, and counters. PageAllocator calls made under it acquire
    # the allocator's own leaf lock inside decode.py (cross-module
    # nesting, declared there) — the cache itself holds exactly one.
    "serve/prefix_cache.py": ("self._lock",),
    # serve/disagg: the PrefillEngine run lock (one pool, one run at a
    # time) is OUTERMOST; PrefillPredictor's executable-construction
    # lock nests under it via _exec_chunk; the module counter lock is a
    # LEAF (_bump after engine state settles, fleetobs discipline).
    "serve/disagg.py": ("self._lock", "self._compile_lock", "_lock"),
    # fleetobs: a FleetRegistry's instance lock guards the per-rank fold
    # state, SLO engine, control-op queue, and stored profiles; the
    # module lock is a LEAF guarding the counter registry and the
    # worker-side beat-cadence/profile-latch state. fold() bumps module
    # counters and fires alert side effects (fault._bump/flight_record)
    # only AFTER releasing the registry lock.
    "fleetobs.py": ("self._lock", "_lock"),
    "serve/predictor.py": ("self._compile_lock",),
    # serve/decode: the scheduler lock (queue + slot tables) is
    # OUTERMOST and never held across device calls; DecodePredictor's
    # executable-construction lock nests under nothing of ours; the
    # PageAllocator free-list lock is a LEAF (alloc under the scheduler
    # lock at admission, free with no lock held at retire).
    "serve/decode.py": ("self._lock", "self._compile_lock",
                        "self._alloc_lock"),
    # serve/spec_decode: the verify-executable construction lock is the
    # module's ONLY lock (single-flight cached_jit build, mirroring
    # DecodePredictor); draft state and adaptive-k live entirely on the
    # scheduler loop thread and need none.
    "serve/spec_decode.py": ("self._compile_lock",),
    # serve/reqtrace: one module lock, a LEAF — it guards the record
    # counter and the exemplar rings and is never held across profiler,
    # I/O, or other-module calls; span booking takes profiler._lock
    # internally only AFTER this lock is released.
    "serve/reqtrace.py": ("_lock",),
    # kvstore_server: update lock outermost (it serializes pushes, like
    # the reference's executor queue); the heartbeat/liveness registry
    # lock is a LEAF — push refreshes liveness only AFTER releasing the
    # update lock, so the two never nest in either direction. The
    # AsyncClient's connection lock is spelled self._lock at its
    # acquisition sites too, but carries the distinct mxsan site
    # "AsyncClient._lock": it serializes one wire conversation, is held
    # across socket I/O by design (BLOCKING_OK below), and never nests
    # with the server-side locks in either direction.
    "kvstore_server.py": ("self._lock", "self._hb_lock",
                          "AsyncClient._lock"),
    "kvstore.py": ("KVStore._class_lock",),
    # fault: AsyncCheckpointManager's queue lock and FaultInjector's hit
    # counter (both spelled self._lock at their sites) stay outermost of
    # the module-level stats-counter leaf lock (_bump runs under _wlock
    # holders' call chains via _commit). The flight-recorder ring lock is
    # a LEAF after it: flight_dump copies the ring under _flight_lock and
    # only then reads stats()/phase_stats() with no lock held.
    "fault.py": ("self._wlock", "self._lock", "_stats_lock",
                 "_flight_lock"),
    "gluon/block.py": ("cls._lock",),
    "symbol/symbol.py": ("cls._lock",),
    "native/__init__.py": ("_lock",),
    # mxsan: the sanitizer's own bookkeeping lock is a LEAF (ring +
    # witness tables + counters, no instrumented code ever runs under
    # it) and, being the instrument itself, is a raw stdlib lock.
    "mxsan.py": ("_lock",),
}

# Cross-module nestings the runtime sanitizer (tools/mxsan) accepts.
# CC02 is lexical and per-module, so it cannot see these; mxsan observes
# them as witness edges at runtime and cross-checks against this table.
# Keys/values are acquisition sites spelled ``<module>:<lock name>``
# with the same module-relative paths and lock spellings as LOCK_ORDER.
# An observed cross-module edge absent here is a SAN02 finding: declare
# it (stating where the nesting sits) or fix the code.
CROSS_MODULE_EDGES = {
    # prefix_cache holds its cache lock while charging/crediting pages
    # through PageAllocator, whose free-list lock is a leaf in decode.py
    # (the nesting LOCK_ORDER's prefix_cache comment promises).
    ("serve/prefix_cache.py:self._lock", "serve/decode.py:self._alloc_lock"),
    # ModelServer's drain/swap lock is held across batcher pause/resume
    # during reload's pause->quiesce->swap->resume; the batcher lock is
    # acquired by the callee and nests strictly under it.
    ("serve/server.py:self._drain_lock", "serve/batcher.py:self._lock"),
    # --- witnessed by the first sanitizer-on run of the test corpus ---
    # cached_jit's single-flight compile lock covers _note_cost, which
    # books the analytical cost model through profiler.cost_event; the
    # compile tracker clock (and the profiler counter lock behind
    # compile_event on the cold path) are leaves under it.
    ("compile_cache.py:self._compile_lock", "profiler.py:_clock"),
    ("compile_cache.py:self._compile_lock", "profiler.py:_lock"),
    # ReplicaAgent._client_locked constructs the kvstore AsyncClient
    # (which takes its connection lock to say hello) under the
    # wire-client-handle lock; ModelServer's ship-client lock does the
    # same lazy construction for KV-page shipping.
    ("serve/control_plane.py:self._lock",
     "kvstore_server.py:AsyncClient._lock"),
    ("serve/server.py:self._ship_lock",
     "kvstore_server.py:AsyncClient._lock"),
    # Decode admission claims prefix pages and bumps serving counters
    # while holding the scheduler lock (the decode.py order comment's
    # "alloc under the scheduler lock at admission").
    ("serve/decode.py:self._lock", "serve/prefix_cache.py:self._lock"),
    ("serve/decode.py:self._lock", "serve/stats.py:self._lock"),
    # The kvstore server applies the jitted optimizer update while
    # holding the update lock (it serializes pushes, like the
    # reference's executor queue), so the cached_jit machinery —
    # single-flight compile lock, fingerprint memo, module LRU, and
    # the profiler compile tracker/counters — all nests under it.
    ("kvstore_server.py:self._lock", "compile_cache.py:self._compile_lock"),
    ("kvstore_server.py:self._lock", "compile_cache.py:self._lock"),
    ("kvstore_server.py:self._lock", "compile_cache.py:_lock"),
    ("kvstore_server.py:self._lock", "profiler.py:_clock"),
    ("kvstore_server.py:self._lock", "profiler.py:_lock"),
    # PrefillEngine.run holds the engine run lock across the whole
    # pool run: chunk execution goes through cached_jit (compile locks,
    # fingerprint memo, LRU + compile tracker), page claiming goes
    # through the prefix cache and PageAllocator, and the final
    # observe() books serving stats — all leaves under the run lock.
    ("serve/disagg.py:self._lock", "compile_cache.py:self._compile_lock"),
    ("serve/disagg.py:self._lock", "compile_cache.py:self._lock"),
    ("serve/disagg.py:self._lock", "compile_cache.py:_lock"),
    ("serve/disagg.py:self._lock", "profiler.py:_clock"),
    ("serve/disagg.py:self._lock", "profiler.py:_lock"),
    ("serve/disagg.py:self._lock", "serve/decode.py:self._alloc_lock"),
    ("serve/disagg.py:self._lock", "serve/prefix_cache.py:self._lock"),
    ("serve/disagg.py:self._lock", "serve/stats.py:self._lock"),
}

# Lock sites (same ``<module>:<lock name>`` spelling) that CC04 and
# SAN03 accept holding across a *bounded* wait.  Each entry is a
# reviewed exception with its justification; an empty table is the
# goal, not a hardship.
BLOCKING_OK = {
    # The AsyncClient connection lock exists to serialize one wire
    # conversation (connect/send/recv with explicit socket timeouts);
    # holding it across the socket calls is the lock's entire job, and
    # nothing else ever nests inside it.
    "kvstore_server.py:AsyncClient._lock",
    # Single-flight native build: the module lock makes every other
    # importer wait for the one g++ run (itself bounded by timeout=120)
    # instead of racing a second compile of the same .so — holding it
    # across the subprocess is the design, exactly like cached_jit's
    # compile lock.
    "native/__init__.py:_lock",
}
