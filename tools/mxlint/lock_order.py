"""Declared lock-acquisition order per module (CC02's ground truth).

The reference engine documented its mutex hierarchy in comments next to the
engine code; here it is a table the linter enforces.  Keys are paths
relative to the package root (``incubator_mxnet_tpu``); values are the
locks a module may hold, in the only order nesting is allowed.  Lock names
are the normalized dotted spelling at the acquisition site (``with
self._lock`` -> ``self._lock``).

A lock acquired in a covered module but absent from its entry is an
*undeclared* lock (CC02): declare it here — stating where a new lock sits
in the hierarchy is the point of the exercise.

Modules not listed are uncovered: CC02 does not fire there (CC01/CC03
still do) — unless the module self-declares its hierarchy with a
top-level ``MXLINT_LOCK_ORDER = ("first", "second")`` tuple, which CC02
then enforces the same way.
"""

LOCK_ORDER = {
    # profiler: event/counter lock, compile-tracker clock, memory book,
    # and track_jit's per-wrapper first-call latch.
    # PR 3's GC deadlock came precisely from violating this file's order.
    "profiler.py": ("_lock", "_clock", "_mlock", "state_lock"),
    # compile_cache: per-wrapper single-flight compile lock outermost
    # (disk/LRU/counter updates nest under it), per-wrapper sig memo and
    # the module LRU+counter lock are leaves.
    "compile_cache.py": ("self._compile_lock", "self._lock", "_lock"),
    # tune: one module lock guards the winner table and counters; the
    # disk tier is written outside it (atomic tmp+rename, last wins).
    "tune.py": ("_lock",),
    # shardlint: one module lock guards the capture buffer, annotation
    # table, and counters; recorders never call out while holding it, so
    # it nests under nothing and nothing nests under it.
    "shardlint.py": ("_lock",),
    "serve/batcher.py": ("self._lock",),
    "serve/stats.py": ("self._lock",),
    # serve/control_plane: a ServeRegistry/ReplicaAgent/RolloutManager
    # instance lock guards its own table or wire client; the module
    # counter lock is a LEAF — _bump and flight_record run only after
    # instance state is settled (fleetobs discipline).
    "serve/control_plane.py": ("self._lock", "_lock"),
    # serve/router: the routing lock (replica table + breakers +
    # round-robin cursor) is OUTERMOST; RouterStats' counter lock is a
    # LEAF. Breaker transitions are recorded (stats/flight/log) only
    # after releasing the routing lock; network calls hold neither.
    "serve/router.py": ("self._rlock", "self._lock"),
    # serve/server: ModelServer's drain/swap lock serializes begin_drain
    # against reload's pause→quiesce→swap→resume; batcher/stats locks
    # are acquired by callees, not nested at this module's sites. The
    # ship-client lock (lazy kvstore client for KV-page shipping) is a
    # LEAF — it guards only client construction/teardown and never
    # nests with the drain lock.
    "serve/server.py": ("self._drain_lock", "self._ship_lock"),
    # serve/prefix_cache: one cache lock guards the radix tree, LRU
    # clock, and counters. PageAllocator calls made under it acquire
    # the allocator's own leaf lock inside decode.py (cross-module
    # nesting, declared there) — the cache itself holds exactly one.
    "serve/prefix_cache.py": ("self._lock",),
    # serve/disagg: the PrefillEngine run lock (one pool, one run at a
    # time) is OUTERMOST; PrefillPredictor's executable-construction
    # lock nests under it via _exec_chunk; the module counter lock is a
    # LEAF (_bump after engine state settles, fleetobs discipline).
    "serve/disagg.py": ("self._lock", "self._compile_lock", "_lock"),
    # fleetobs: a FleetRegistry's instance lock guards the per-rank fold
    # state, SLO engine, control-op queue, and stored profiles; the
    # module lock is a LEAF guarding the counter registry and the
    # worker-side beat-cadence/profile-latch state. fold() bumps module
    # counters and fires alert side effects (fault._bump/flight_record)
    # only AFTER releasing the registry lock.
    "fleetobs.py": ("self._lock", "_lock"),
    "serve/predictor.py": ("self._compile_lock",),
    # serve/decode: the scheduler lock (queue + slot tables) is
    # OUTERMOST and never held across device calls; DecodePredictor's
    # executable-construction lock nests under nothing of ours; the
    # PageAllocator free-list lock is a LEAF (alloc under the scheduler
    # lock at admission, free with no lock held at retire).
    "serve/decode.py": ("self._lock", "self._compile_lock",
                        "self._alloc_lock"),
    # serve/spec_decode: the verify-executable construction lock is the
    # module's ONLY lock (single-flight cached_jit build, mirroring
    # DecodePredictor); draft state and adaptive-k live entirely on the
    # scheduler loop thread and need none.
    "serve/spec_decode.py": ("self._compile_lock",),
    # serve/reqtrace: one module lock, a LEAF — it guards the record
    # counter and the exemplar rings and is never held across profiler,
    # I/O, or other-module calls; span booking takes profiler._lock
    # internally only AFTER this lock is released.
    "serve/reqtrace.py": ("_lock",),
    # kvstore_server: update lock outermost (it serializes pushes, like
    # the reference's executor queue); the heartbeat/liveness registry
    # lock is a LEAF — push refreshes liveness only AFTER releasing the
    # update lock, so the two never nest in either direction. The
    # AsyncClient's connection lock is also spelled self._lock.
    "kvstore_server.py": ("self._lock", "self._hb_lock"),
    "kvstore.py": ("KVStore._class_lock",),
    # fault: AsyncCheckpointManager's queue lock and FaultInjector's hit
    # counter (both spelled self._lock at their sites) stay outermost of
    # the module-level stats-counter leaf lock (_bump runs under _wlock
    # holders' call chains via _commit). The flight-recorder ring lock is
    # a LEAF after it: flight_dump copies the ring under _flight_lock and
    # only then reads stats()/phase_stats() with no lock held.
    "fault.py": ("self._wlock", "self._lock", "_stats_lock",
                 "_flight_lock"),
    "gluon/block.py": ("cls._lock",),
    "symbol/symbol.py": ("cls._lock",),
    "native/__init__.py": ("_lock",),
}
