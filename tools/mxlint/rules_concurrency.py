"""Concurrency rules (CC01-CC04).

CC01 — an attribute that is guarded by a lock *somewhere* in its class
(read-modify-written inside ``with self._lock``) must be guarded
*everywhere* it is read-modify-written; a lone unlocked ``self.x += 1``
next to locked updates is exactly the racy ``Counter.increment`` PR 3
fixed by hand.  Class attributes get the stricter form: any
``Cls.attr += 1`` style RMW with no lock held is flagged, because class
counters are shared across every instance and thread by construction.

CC02 — nested lock acquisition must follow the order declared in
``lock_order.LOCK_ORDER``; acquiring a lock the module never declared is
flagged too.  This is the static form of the hierarchy whose violation
gave PR 3 its GC finalizer deadlock.

CC03 — calling, while a lock is held, a same-module function that
acquires that same lock: ``threading.Lock`` is not reentrant, so this is
a guaranteed self-deadlock.  The in-tree convention is that helpers named
``*_locked`` expect the caller to hold the lock; the rule understands it.

CC04 — a known-blocking call (``time.sleep``, un-timed ``Thread.join``,
un-timed ``queue.get``, ``subprocess.*``, socket connect/accept/
recv/sendall) lexically inside a ``with <lock>`` body stalls every
other waiter on that lock for the duration; the same call inside a
``*_locked``-contract function blocks the *caller's* lock just as
surely.  Timed variants (``join(timeout=...)``, ``get(timeout=...)``)
are bounded waits and pass.  Locks whose whole purpose is to serialize
an I/O conversation are allowed via ``lock_order.BLOCKING_OK`` — the
leaf-lock allowance the runtime sanitizer (SAN03) shares.

Functions named ``*_locked`` are exempt from CC01 (their contract is
"caller holds the lock"), as is ``__init__`` (no concurrent access before
construction completes).
"""
from __future__ import annotations

import ast

from .core import Finding, dotted, lock_key, root_name
from .lock_order import BLOCKING_OK, LOCK_ORDER


def _order_for(mod):
    rel = mod.relpath.replace("\\", "/")
    for key, order in LOCK_ORDER.items():
        if rel.endswith("incubator_mxnet_tpu/" + key) or rel == key:
            return order
    # a module outside the registry may self-declare its hierarchy with a
    # top-level `MXLINT_LOCK_ORDER = ("first", "second")` tuple
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "MXLINT_LOCK_ORDER":
                return tuple(
                    n.value for n in getattr(node.value, "elts", ())
                    if isinstance(n, ast.Constant) and
                    isinstance(n.value, str))
    return None


def _fn_name_chain(node):
    """Name of the function enclosing `node`, '' at module level."""
    n = getattr(node, "mx_parent", None)
    while n is not None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return n.name
        n = getattr(n, "mx_parent", None)
    return ""


def _with_locks(node):
    """Lock keys acquired by a With statement (usually one)."""
    keys = []
    if isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            k = lock_key(item.context_expr)
            if k is not None:
                keys.append(k)
    return keys


def _held_locks(node):
    """Lock keys held at `node`, outermost first."""
    held = []
    n = getattr(node, "mx_parent", None)
    while n is not None:
        for k in _with_locks(n):
            held.append(k)
        n = getattr(n, "mx_parent", None)
    held.reverse()
    return held


def _is_rmw(stmt):
    """True for an AugAssign, or an Assign whose RHS reads the target."""
    if isinstance(stmt, ast.AugAssign):
        return True
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        target = dotted(stmt.targets[0])
        if target is None:
            return False
        for n in ast.walk(stmt.value):
            if isinstance(n, (ast.Attribute, ast.Name)) and \
                    dotted(n) == target and isinstance(n.ctx, ast.Load):
                return True
    return False


def _cc01(mod, findings):
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        # pass 1: attributes RMW'd under a self/cls lock anywhere in class
        guarded = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            if not _is_rmw(node):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            held = _held_locks(node)
            if not held:
                continue
            for t in targets:
                d = dotted(t if not isinstance(t, ast.Subscript)
                           else t.value)
                if d and root_name(t) in ("self", "cls"):
                    guarded.setdefault(d, held[0])
        # pass 2: the same attributes RMW'd with no lock held
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            if not _is_rmw(node) or _held_locks(node):
                continue
            fn = _fn_name_chain(node)
            if fn.endswith("_locked"):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                d = dotted(t if not isinstance(t, ast.Subscript)
                           else t.value)
                if d is None:
                    continue
                if d.split(".")[0] in mod.class_names or \
                        d.startswith("cls."):
                    # class attributes are shared across every instance
                    # and thread by construction — __init__ is not safe
                    findings.append(Finding(
                        "CC01", mod.relpath, node.lineno, node.col_offset,
                        f"class attribute `{d}` read-modify-written "
                        f"without a lock; shared across all threads"))
                elif d in guarded and fn != "__init__":
                    findings.append(Finding(
                        "CC01", mod.relpath, node.lineno, node.col_offset,
                        f"`{d}` is updated under `{guarded[d]}` elsewhere "
                        f"in `{cls.name}` but read-modify-written here "
                        f"without it"))


def _cc01_module_globals(mod, findings):
    """Module-level analog: globals RMW'd under a module lock somewhere
    must not be RMW'd lock-free elsewhere."""
    guarded = {}
    bare = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.AugAssign):
            continue
        if not isinstance(node.target, ast.Name):
            continue
        fn = _fn_name_chain(node)
        if fn.endswith("_locked"):
            continue
        held = _held_locks(node)
        # only globals: name declared `global` in the enclosing fn, or
        # the statement sits at module level
        is_global = isinstance(getattr(node, "mx_parent", None), ast.Module)
        n = getattr(node, "mx_parent", None)
        while n is not None and not is_global:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for stmt in ast.walk(n):
                    if isinstance(stmt, ast.Global) and \
                            node.target.id in stmt.names:
                        is_global = True
                break
            n = getattr(n, "mx_parent", None)
        if not is_global:
            continue
        if held:
            guarded.setdefault(node.target.id, held[0])
        else:
            bare.append(node)
    for node in bare:
        if node.target.id in guarded:
            findings.append(Finding(
                "CC01", mod.relpath, node.lineno, node.col_offset,
                f"global `{node.target.id}` is updated under "
                f"`{guarded[node.target.id]}` elsewhere but "
                f"read-modify-written here without it"))


def _normalize(key, order):
    """Match an acquisition spelling against a declared name: exact, or
    same terminal attribute (`self._lock` vs `_lock` never conflated —
    both sides must agree on the full dotted form)."""
    return key if key in order else None


def _cc02(mod, findings):
    order = _order_for(mod)
    if order is None:
        return
    rank = {name: i for i, name in enumerate(order)}
    for node in ast.walk(mod.tree):
        keys = _with_locks(node)
        if not keys:
            continue
        held = _held_locks(node)
        for k in keys:
            if k not in rank:
                findings.append(Finding(
                    "CC02", mod.relpath, node.lineno, node.col_offset,
                    f"lock `{k}` is not declared in the lock-order "
                    f"registry for this module"))
                continue
            for h in held:
                if h in rank and rank[h] > rank[k]:
                    findings.append(Finding(
                        "CC02", mod.relpath, node.lineno, node.col_offset,
                        f"acquiring `{k}` while holding `{h}` inverts "
                        f"the declared order {order}"))


def _locks_taken_by(fn):
    """Lock keys a function acquires anywhere in its own body (not in
    nested defs)."""
    taken = set()
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        taken.update(_with_locks(node))
        for child in ast.iter_child_nodes(node):
            stack.append(child)
    return taken


def _cc03(mod, findings):
    # map function name -> locks it takes (module + class methods)
    takes = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            locks = _locks_taken_by(node)
            if locks:
                takes.setdefault(node.name, set()).update(locks)
    for node in ast.walk(mod.tree):
        # direct re-entry: with L: ... with L:
        for k in _with_locks(node):
            if k in _held_locks(node):
                findings.append(Finding(
                    "CC03", mod.relpath, node.lineno, node.col_offset,
                    f"`{k}` acquired while already held "
                    f"(threading.Lock self-deadlocks)"))
        # call under lock to a function that takes the same lock
        if isinstance(node, ast.Call):
            held = set(_held_locks(node))
            if not held:
                continue
            fname = dotted(node.func)
            if fname is None:
                continue
            # only bare / self. / cls. calls can hit a same-module def;
            # `self._thread.start()` is some other object's method
            if fname.count(".") > 1 or (
                    "." in fname and
                    fname.split(".")[0] not in ("self", "cls")):
                continue
            callee = fname.split(".")[-1]
            if callee.endswith("_locked"):
                continue  # contract: caller holds the lock, callee doesn't
            overlap = takes.get(callee, set()) & held
            if overlap:
                k = sorted(overlap)[0]
                findings.append(Finding(
                    "CC03", mod.relpath, node.lineno, node.col_offset,
                    f"`{callee}()` acquires `{k}`, which is already held "
                    f"at this call site"))


_SOCKET_METHODS = ("connect", "accept", "recv", "recv_into", "sendall")
_QUEUE_HINTS = ("queue", "_q", "q")


def _modkey(mod):
    """This module's LOCK_ORDER key ('' when unregistered)."""
    rel = mod.relpath.replace("\\", "/")
    for key in LOCK_ORDER:
        if rel.endswith("incubator_mxnet_tpu/" + key) or rel == key:
            return key
    return ""


def _class_name_chain(node):
    """Name of the class enclosing `node`, '' at module level."""
    n = getattr(node, "mx_parent", None)
    while n is not None:
        if isinstance(n, ast.ClassDef):
            return n.name
        n = getattr(n, "mx_parent", None)
    return ""


def _queue_like(recv):
    """Receiver name suggests a queue (`self._queue`, `work_q`, `q`)."""
    if recv is None:
        return False
    tail = recv.split(".")[-1].lower()
    return ("queue" in tail or tail == "q" or tail.endswith("_q"))


def _blocking_kind(call):
    """What un-bounded wait this Call is, or None."""
    fname = dotted(call.func)
    kwargs = {kw.arg for kw in call.keywords}
    if fname == "time.sleep":
        return "time.sleep"
    if fname is not None and fname.startswith("subprocess."):
        return fname
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv = dotted(call.func.value)
    if attr in _SOCKET_METHODS or (
            fname is not None and fname.startswith("socket.") and
            attr == "create_connection"):
        return "socket-ish .%s" % attr
    if attr == "join" and not call.args and "timeout" not in kwargs:
        # zero-argument join is Thread.join (str.join always takes the
        # iterable); a timeout keyword makes it a bounded wait
        return ".join()"
    if attr == "get" and not call.args and "timeout" not in kwargs and \
            _queue_like(recv):
        return "un-timed queue .get()"
    return None


def _cc04(mod, findings):
    modkey = _modkey(mod)

    def _allowed(lock, cls):
        # leaf-lock allowance: the site (or its class-qualified mxsan
        # spelling, e.g. AsyncClient._lock for self._lock) is declared
        # safe to hold across a bounded wait in lock_order.BLOCKING_OK
        if not modkey:
            return False
        if "%s:%s" % (modkey, lock) in BLOCKING_OK:
            return True
        if lock.startswith("self.") and cls:
            return "%s:%s.%s" % (modkey, cls, lock[5:]) in BLOCKING_OK
        return False

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _blocking_kind(node)
        if kind is None:
            continue
        cls = _class_name_chain(node)
        held = _held_locks(node)
        if held:
            live = [k for k in held if not _allowed(k, cls)]
            if not live:
                continue
            findings.append(Finding(
                "CC04", mod.relpath, node.lineno, node.col_offset,
                f"blocking {kind} while holding `{live[-1]}`; every "
                f"other waiter stalls for the full wait"))
            continue
        fn = _fn_name_chain(node)
        if fn.endswith("_locked") and not fn.startswith("__"):
            # the contract lock is the caller's; a class-qualified
            # BLOCKING_OK entry (e.g. AsyncClient._lock) covers every
            # *_locked method of that class
            if modkey and cls and any(
                    w.startswith("%s:%s." % (modkey, cls))
                    for w in BLOCKING_OK):
                continue
            findings.append(Finding(
                "CC04", mod.relpath, node.lineno, node.col_offset,
                f"blocking {kind} inside `{fn}` (the *_locked contract "
                f"means the caller is holding the lock)"))


def check(mod):
    findings = []
    _cc01(mod, findings)
    _cc01_module_globals(mod, findings)
    _cc02(mod, findings)
    _cc03(mod, findings)
    _cc04(mod, findings)
    return findings
