"""Trace-safety rules (TS01-TS04).

A function is *traced* when jax runs it once with abstract tracers to
build an XLA program: op bodies registered through ``ops.registry.register``,
anything decorated with / passed to ``jax.jit``, and the callables handed to
``profiler.track_jit``.  Inside such a function the Python code is a
metaprogram — host side effects run at trace time only (TS01), ``if``/
``while`` on traced values raises or silently specializes (TS02), storing a
tracer into host state leaks it (TS03), and a closure-captured array is
baked into the executable as a constant, recompiling whenever it changes
(TS04 — the class of silent recompile PR 3's runtime tracker can only
detect after the fact).
"""
from __future__ import annotations

import ast

from .core import Finding, dotted, root_name

# calls that are host side effects regardless of module (TS01)
_HOST_BUILTINS = {"print", "input", "open", "breakpoint", "exec", "eval"}
# attribute chains rooted at the `os` module that touch host state
_OS_HOST = {"environ", "getenv", "putenv", "system", "popen", "remove",
            "unlink", "makedirs", "mkdir", "rename", "urandom"}
# shape-like attributes that are static at trace time (TS02 allowance)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type"}
_STATIC_CALLS = {"len", "isinstance", "issubclass", "callable", "hasattr",
                 "getattr", "type"}
# numpy/jax constructors whose result is an array value (TS04 evidence)
_ARRAY_CTORS = {"array", "asarray", "zeros", "ones", "full", "empty",
                "arange", "linspace", "eye", "device_put", "asnumpy"}


class TracedFn:
    """One function the linter believes jax will trace."""

    __slots__ = ("node", "kind", "traced_params")

    def __init__(self, node, kind, traced_params):
        self.node = node
        self.kind = kind          # "op" | "jit" | "track_jit"
        self.traced_params = traced_params  # names holding tracer values

    @property
    def name(self):
        return getattr(self.node, "name", "<lambda>")


def _decorator_call(dec):
    """(dotted name of decorator callable, Call node or None)."""
    if isinstance(dec, ast.Call):
        return dotted(dec.func), dec
    return dotted(dec), None


def _kw(call, name):
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _is_true(node):
    return isinstance(node, ast.Constant) and node.value is True


def _positional_params(fn):
    """Positional/vararg parameter names (the tracer-carrying ones); a
    leading self/cls is host state, not a tracer."""
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    if args.vararg:
        names.append(args.vararg.arg)
    return set(names)


def _jit_names(mod):
    """Local spellings that resolve to jax.jit: 'jax' aliases give
    '<alias>.jit', plus `from jax import jit [as j]`."""
    chains = set()
    for alias in mod.aliases_of("jax"):
        chains.add(alias + ".jit")
    for local in mod.from_import_names("jit", "jax"):
        chains.add(local)
    return chains


def _track_jit_names(mod):
    """Spellings of profiler.track_jit: from-imports of track_jit, plus
    '<alias>.track_jit' for any imported module named/aliased profiler."""
    chains = set(mod.from_import_names("track_jit"))
    for local, modpath in mod.import_aliases.items():
        if modpath.split(".")[-1] == "profiler":
            chains.add(local + ".track_jit")
    for local, (src, orig) in mod.from_imports.items():
        if orig == "profiler":
            chains.add(local + ".track_jit")
    return chains


def _cached_jit_names(mod):
    """Spellings of compile_cache.cached_jit: the two-tier executable
    cache wraps a traced callable exactly like track_jit(key, fn) does
    (arg index 1), so its call sites keep full trace-safety coverage."""
    chains = set(mod.from_import_names("cached_jit"))
    for local, modpath in mod.import_aliases.items():
        if modpath.split(".")[-1] == "compile_cache":
            chains.add(local + ".cached_jit")
    for local, (src, orig) in mod.from_imports.items():
        if orig == "compile_cache":
            chains.add(local + ".cached_jit")
    return chains


def _tuned_call_names(mod):
    """Spellings of tune.tuned_call: the autotuner dispatches its XLA
    fallback (arg index 1, after the kernel name) under jit exactly like
    cached_jit(key, fn), so the fallback keeps trace-safety coverage."""
    chains = set(mod.from_import_names("tuned_call"))
    for local, modpath in mod.import_aliases.items():
        if modpath.split(".")[-1] == "tune":
            chains.add(local + ".tuned_call")
    for local, (src, orig) in mod.from_imports.items():
        if orig == "tune":
            chains.add(local + ".tuned_call")
    return chains


def _register_names(mod):
    """Spellings of ops.registry.register (from-imports only; every
    in-tree user does `from .registry import register`)."""
    return mod.from_import_names("register", "registry")


def _local_functions(scope):
    """name -> FunctionDef for defs directly inside `scope`'s body."""
    out = {}
    for stmt in ast.walk(scope):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(stmt.name, stmt)
    return out


def discover_traced(mod):
    """All TracedFn in a module."""
    found = {}

    def add(node, kind):
        if id(node) in found:
            return
        if isinstance(node, ast.Lambda):
            params = {a.arg for a in node.args.args + node.args.posonlyargs}
            if node.args.vararg:
                params.add(node.args.vararg.arg)
            found[id(node)] = TracedFn(node, kind, params)
        else:
            found[id(node)] = TracedFn(node, kind, _positional_params(node))

    jit_chains = _jit_names(mod)
    track_chains = (_track_jit_names(mod) | _cached_jit_names(mod)
                    | _tuned_call_names(mod))
    reg_names = _register_names(mod)
    fn_table = _local_functions(mod.tree)

    def resolve(arg):
        """Turn a jit()/track_jit() argument into a function node."""
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return fn_table.get(arg.id)
        return None

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                name, call = _decorator_call(dec)
                if name in reg_names:
                    if call is not None and _is_true(_kw(call, "eager_only")):
                        continue  # never traced
                    add(node, "op")
                elif name in jit_chains:
                    add(node, "jit")
                elif name is not None and name.endswith("partial") and \
                        call is not None and call.args and \
                        dotted(call.args[0]) in jit_chains:
                    add(node, "jit")
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in jit_chains and node.args:
                target = resolve(node.args[0])
                if target is not None:
                    add(target, "jit")
            elif name in track_chains and len(node.args) >= 2:
                target = resolve(node.args[1])
                if target is not None:
                    add(target, "track_jit")
    return list(found.values())


# -- TS01 -------------------------------------------------------------------

def _host_call_reason(call, mod):
    fname = dotted(call.func)
    if fname in _HOST_BUILTINS:
        return f"call to `{fname}()`"
    if fname is None:
        return None
    parts = fname.split(".")
    head = parts[0]
    imported = mod.import_aliases.get(head)
    if imported == "numpy" and len(parts) >= 2 and parts[1] == "random":
        return f"call to `{fname}()` (host RNG; results freeze at trace time)"
    if imported == "os" and len(parts) >= 2 and parts[1] in _OS_HOST:
        return f"call to `{fname}()` (host OS access)"
    return None


def _ts01(mod, tf, findings):
    for node in ast.walk(tf.node):
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            # time.time() / time.monotonic() style, via real module alias
            if fname is not None:
                head = fname.split(".")[0]
                if mod.import_aliases.get(head) == "time":
                    findings.append(Finding(
                        "TS01", mod.relpath, node.lineno, node.col_offset,
                        f"`{fname}()` inside traced `{tf.name}` runs at "
                        f"trace time, not per step"))
                    continue
            reason = _host_call_reason(node, mod)
            if reason:
                findings.append(Finding(
                    "TS01", mod.relpath, node.lineno, node.col_offset,
                    f"{reason} inside traced `{tf.name}`"))
        elif isinstance(node, ast.Subscript):
            d = dotted(node.value)
            if d is not None:
                head = d.split(".")[0]
                if mod.import_aliases.get(head) == "os" and \
                        d.endswith(".environ"):
                    findings.append(Finding(
                        "TS01", mod.relpath, node.lineno, node.col_offset,
                        f"`{d}[...]` read inside traced `{tf.name}`"))


# -- TS02 -------------------------------------------------------------------

def _mentions_traced_value(test, traced):
    """True when `test` depends on a traced parameter in a way that is
    dynamic at trace time (not .shape/.ndim/len()/isinstance/is-None)."""
    def dynamic_names(node):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return set()
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname in _STATIC_CALLS:
                return set()
            out = set()
            for a in node.args:
                out |= dynamic_names(a)
            for k in node.keywords:
                out |= dynamic_names(k.value)
            return out
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return set()
            out = dynamic_names(node.left)
            for c in node.comparators:
                out |= dynamic_names(c)
            return out
        if isinstance(node, ast.Name):
            return {node.id}
        out = set()
        for child in ast.iter_child_nodes(node):
            out |= dynamic_names(child)
        return out

    return bool(dynamic_names(test) & traced)


def _ts02(mod, tf, findings):
    body = tf.node.body if not isinstance(tf.node, ast.Lambda) else []
    stack = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # nested defs have their own tracer params
        if isinstance(node, (ast.If, ast.While)):
            if _mentions_traced_value(node.test, tf.traced_params):
                kw = "while" if isinstance(node, ast.While) else "if"
                findings.append(Finding(
                    "TS02", mod.relpath, node.lineno, node.col_offset,
                    f"`{kw}` condition in traced `{tf.name}` depends on a "
                    f"traced value"))
        for child in ast.iter_child_nodes(node):
            stack.append(child)


# -- TS03 -------------------------------------------------------------------

def _collect_locals(fn):
    """Names bound inside `fn` itself (params, assignments, loops, withs,
    comprehensions, nested defs)."""
    names = set()
    a = fn.args
    for arg in a.posonlyargs + a.args + a.kwonlyargs:
        names.add(arg.arg)
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
            continue
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.comprehension,)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    names.add(n.id)
        for child in ast.iter_child_nodes(node):
            stack.append(child)
    return names


def _ts03(mod, tf, findings):
    """Stores whose target roots outside the traced function: self.x = ...,
    global/nonlocal writes, and subscript/attribute stores on closure
    names.  Checked for the traced fn and any defs nested in it (they
    trace together)."""
    def check_fn(fn, fn_locals):
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_fn(node, fn_locals | _collect_locals(node))
                continue
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                findings.append(Finding(
                    "TS03", mod.relpath, node.lineno, node.col_offset,
                    f"`{type(node).__name__.lower()}` write inside traced "
                    f"`{tf.name}` leaks trace-time state"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if not isinstance(t, (ast.Attribute, ast.Subscript)):
                        continue
                    root = root_name(t)
                    if root is None or root in fn_locals:
                        continue
                    findings.append(Finding(
                        "TS03", mod.relpath, node.lineno, node.col_offset,
                        f"store to `{dotted(t) or root}` in traced "
                        f"`{tf.name}` writes host state during tracing"))
            for child in ast.iter_child_nodes(node):
                stack.append(child)

    if isinstance(tf.node, ast.Lambda):
        return
    check_fn(tf.node, _collect_locals(tf.node))


# -- TS04 -------------------------------------------------------------------

def _array_bindings(scope, mod):
    """Names in `scope` whose binding makes them look like concrete arrays:
    assigned from a numpy/jnp/jax constructor call, `.asnumpy()`,
    `.data()` or `._data` access."""
    np_like = set()
    for local, path in mod.import_aliases.items():
        if path in ("numpy", "jax.numpy", "jax"):
            np_like.add(local)
    arrays = set()
    for stmt in scope.body if isinstance(scope.body, list) else []:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not stmt:
                continue
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            is_array = False
            if isinstance(val, ast.Call):
                fname = dotted(val.func)
                if fname:
                    parts = fname.split(".")
                    if parts[0] in np_like and parts[-1] in _ARRAY_CTORS:
                        is_array = True
                    elif parts[-1] in ("asnumpy", "data"):
                        is_array = True
            elif isinstance(val, ast.Attribute) and val.attr == "_data":
                is_array = True
            if not is_array:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    arrays.add(t.id)
    return arrays


def _ts04(mod, tf, findings):
    """Free names in a nested traced fn whose enclosing-scope binding is
    array-like: jit will bake the value in as a constant."""
    fn = tf.node
    if isinstance(fn, ast.Lambda):
        return
    enclosing = getattr(fn, "mx_parent", None)
    while enclosing is not None and not isinstance(
            enclosing, (ast.FunctionDef, ast.AsyncFunctionDef)):
        enclosing = getattr(enclosing, "mx_parent", None)
    if enclosing is None:
        return  # module-level fn: captures are module constants
    fn_locals = _collect_locals(fn)
    candidates = _array_bindings(enclosing, mod)
    if not candidates:
        return
    reported = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in fn_locals or node.id in mod.module_names:
                continue
            if node.id not in candidates or node.id in reported:
                continue
            # names only used in call position are functions, not arrays
            parent = getattr(node, "mx_parent", None)
            if isinstance(parent, ast.Call) and parent.func is node:
                continue
            reported.add(node.id)
            findings.append(Finding(
                "TS04", mod.relpath, node.lineno, node.col_offset,
                f"traced `{tf.name}` closes over array `{node.id}`; it "
                f"becomes a compile-time constant"))


def check(mod):
    findings = []
    for tf in discover_traced(mod):
        _ts01(mod, tf, findings)
        _ts02(mod, tf, findings)
        _ts03(mod, tf, findings)
        _ts04(mod, tf, findings)
    return findings
