"""Env-var hygiene rules (EV01-EV02).

Every ``MXNET_*`` / ``MXTPU_*`` knob must be read through the
``util.getenv_int/getenv_bool/getenv_str`` helpers, whose defaults and
descriptions live in the single ``util.ENV_VARS`` registry (EV01), and
every name passed to those helpers must actually be declared there (EV02).
The registry is recovered by *parsing* util.py, never importing it, so the
linter stays independent of jax and runs anywhere.
"""
from __future__ import annotations

import ast
import os

from .core import Finding, dotted

_PREFIXES = ("MXNET_", "MXTPU_")
_HELPERS = {"getenv_int", "getenv_bool", "getenv_str"}


def _defines_registry(mod):
    """True when the module assigns a top-level ENV_VARS — that module
    (util.py) is the one place raw reads are allowed."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "ENV_VARS":
                    return True
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) and \
                    node.target.id == "ENV_VARS":
                return True
    return False


def load_registry(package_root):
    """Declared env-var names, by parsing <package_root>/util.py.
    Returns None when util.py has no ENV_VARS yet (EV02 then skips)."""
    path = os.path.join(package_root, "util.py")
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "ENV_VARS"
                   for t in node.targets):
            continue
        names = set()
        for n in ast.walk(node.value):
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and n.value.startswith(_PREFIXES):
                names.add(n.value)
        return names
    return None


def _literal_env_name(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str) and \
            node.value.startswith(_PREFIXES):
        return node.value
    return None


def check(mod, registry=None):
    findings = []
    if _defines_registry(mod):
        return findings  # util.py itself implements the helpers
    os_aliases = mod.aliases_of("os")
    environ_chains = {a + ".environ" for a in os_aliases}
    environ_chains |= set(mod.from_import_names("environ", "os"))
    getenv_chains = {a + ".getenv" for a in os_aliases}
    getenv_chains |= {a + ".environ.get" for a in os_aliases}
    getenv_chains |= set(mod.from_import_names("getenv", "os"))

    for node in ast.walk(mod.tree):
        # EV01: os.environ["MXNET_X"], os.environ.get("MXNET_X"),
        # os.getenv("MXNET_X")
        name = None
        if isinstance(node, ast.Subscript):
            if dotted(node.value) in environ_chains:
                name = _literal_env_name(
                    node.slice if not isinstance(node.slice, ast.Index)
                    else node.slice.value)
        elif isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname in getenv_chains and node.args:
                name = _literal_env_name(node.args[0])
            elif fname is not None and \
                    fname.split(".")[-1] in _HELPERS:
                # EV02: helper called with an undeclared name
                if node.args:
                    ev = _literal_env_name(node.args[0])
                    if ev is not None and registry is not None and \
                            ev not in registry:
                        findings.append(Finding(
                            "EV02", mod.relpath, node.lineno,
                            node.col_offset,
                            f"`{ev}` is read via "
                            f"{fname.split('.')[-1]} but not declared "
                            f"in util.ENV_VARS"))
                continue
        if name is not None:
            findings.append(Finding(
                "EV01", mod.relpath, node.lineno, node.col_offset,
                f"raw environment read of `{name}` bypasses "
                f"util.ENV_VARS"))
    return findings
