"""mxlint core: per-module AST model shared by every rule family.

The reference framework's invariants (NNVM op purity for the dependency
engine's var-version chains, engine-callback lock discipline) were enforced
only by review. Our JAX port carries the same invariants in Python form;
this package encodes them as automated passes over stdlib `ast` — the TVM
move of turning IR invariants into passes instead of review lore.

A ModuleInfo is built once per file and handed to each rule family:

  * parent links (`mx_parent`) so rules can ask "what encloses this node"
  * import alias tables (``import numpy as _np`` -> _np: numpy) so rules
    match *modules*, not spellings
  * a suppression map parsed from ``# mxlint: disable=RULE(reason)``
    comments — a disable with an EMPTY reason does not suppress, so every
    in-tree suppression documents itself
"""
from __future__ import annotations

import ast
import re

__all__ = ["RULES", "Finding", "ModuleInfo", "dotted", "root_name",
           "enclosing_function", "lock_key"]

# rule id -> (one-line title, fix hint)
RULES = {
    "TS01": (
        "host side effect in traced code",
        "hoist the call out of the traced function, or use the jax "
        "equivalent (jax.random.*, jax.debug.print, jax.debug.callback)"),
    "TS02": (
        "python branch on a traced value",
        "use jnp.where / lax.cond / lax.while_loop, or make the value a "
        "static (keyword-only) parameter"),
    "TS03": (
        "traced value may leak into host state",
        "return the value instead of writing it to self/globals/closures; "
        "tracer leaks poison later calls and block jit caching"),
    "TS04": (
        "closure-captured array baked into a jit constant",
        "pass the array as an argument (or bind it via a default arg); a "
        "captured array recompiles the executable every time it changes"),
    "CC01": (
        "read-modify-write outside the guarding lock",
        "take the same lock that guards this attribute elsewhere (or move "
        "the update into a *_locked helper called under it)"),
    "CC02": (
        "lock acquisition violates the declared lock order",
        "acquire locks in the order declared in tools/mxlint/lock_order.py "
        "(or declare the new lock there)"),
    "CC03": (
        "function that takes this lock called while it is held",
        "call the *_locked variant, or restructure so the lock is "
        "released first (threading.Lock is not reentrant)"),
    "CC04": (
        "blocking call while holding a lock",
        "move the sleep/join/un-timed get/subprocess/socket call outside "
        "the with-lock body, give the wait a timeout, or add the lock "
        "site to BLOCKING_OK in tools/mxlint/lock_order.py with a "
        "justification"),
    "EV01": (
        "raw os.environ read of an MXNET_*/MXTPU_* variable",
        "route through util.getenv_int/getenv_bool/getenv_str so the "
        "default and doc live in util.ENV_VARS"),
    "EV02": (
        "environment variable not declared in util.ENV_VARS",
        "add the variable (default + description) to util.ENV_VARS"),
}

_SUPP_ITEM = re.compile(r"([A-Z]{2}\d{2})\(([^)]*)\)")
_SUPP_RE = re.compile(r"#\s*mxlint:\s*disable=")


class Finding:
    """One rule violation at file:line, with a fix hint."""

    __slots__ = ("rule", "path", "line", "col", "message", "hint",
                 "suppress_reason")

    def __init__(self, rule, path, line, col, message):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.hint = RULES[rule][1]
        self.suppress_reason = None

    def as_dict(self):
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message, "hint": self.hint}
        if self.suppress_reason is not None:
            d["suppressed"] = self.suppress_reason
        return d

    def render(self):
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}\n    hint: {self.hint}")


def dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_name(node):
    """Base Name of an Attribute/Subscript/Call chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else None


def enclosing_function(node):
    """Nearest enclosing FunctionDef/Lambda (via mx_parent), else None."""
    n = getattr(node, "mx_parent", None)
    while n is not None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return n
        n = getattr(n, "mx_parent", None)
    return None


def lock_key(expr):
    """Normalized dotted name for a with-item that looks like a lock
    ('self._lock', '_mlock', 'cls._lock', 'KVStore._class_lock'), else
    None. A context manager qualifies when its terminal name segment
    contains 'lock'."""
    d = dotted(expr)
    if d is None:
        return None
    if "lock" in d.rsplit(".", 1)[-1].lower():
        return d
    return None


class ModuleInfo:
    """Parsed module + the cross-rule symbol/alias/suppression tables."""

    def __init__(self, path, src, relpath=None):
        self.path = path
        self.relpath = relpath or path
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.lines = src.splitlines()
        self._link_parents()
        self.import_aliases = {}   # local name -> imported module path
        self.from_imports = {}     # local name -> (module, original name)
        self.module_names = set()  # every top-level binding
        self.class_names = set()
        self._collect_bindings()
        self.suppressions = self._parse_suppressions()

    # -- structure ---------------------------------------------------------
    def _link_parents(self):
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child.mx_parent = parent

    def _collect_bindings(self):
        # imports are collected from the WHOLE tree: this codebase lazily
        # imports jax/os inside functions, and an alias means the same
        # module wherever it appears
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.import_aliases[local] = a.name
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    local = a.asname or a.name
                    self.from_imports[local] = (node.module or "", a.name)
        for node in self.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    self.module_names.add(
                        a.asname or a.name.split(".")[0])
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                self.module_names.add(node.name)
                if isinstance(node, ast.ClassDef):
                    self.class_names.add(node.name)
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.module_names.add(n.id)

    def aliases_of(self, module):
        """Local names bound to `module` (exact match on the import path)."""
        return {local for local, mod in self.import_aliases.items()
                if mod == module}

    def from_import_names(self, original, module_suffix=None):
        """Local names for `from X import original` (optionally requiring
        X to end with module_suffix, dots-insensitive)."""
        out = set()
        for local, (mod, orig) in self.from_imports.items():
            if orig != original:
                continue
            if module_suffix is not None:
                if not mod.lstrip(".").endswith(module_suffix) and \
                        mod.lstrip(".") != module_suffix:
                    continue
            out.add(local)
        return out

    # -- suppressions ------------------------------------------------------
    def _parse_suppressions(self):
        supp = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPP_RE.search(line)
            if not m:
                continue
            for rule, reason in _SUPP_ITEM.findall(line[m.end():]):
                supp.setdefault(i, {})[rule] = reason.strip()
        return supp

    def suppression_for(self, rule, line):
        """Reason string when `rule` is disabled at `line` — the disable
        comment may sit on the line itself or on a pure-comment line
        directly above. Empty reasons never suppress."""
        for cand in (line, line - 1):
            reasons = self.suppressions.get(cand)
            if not reasons or rule not in reasons:
                continue
            if cand == line - 1:
                text = self.lines[cand - 1].lstrip()
                if not text.startswith("#"):
                    continue
            reason = reasons[rule]
            if reason:
                return reason
        return None
