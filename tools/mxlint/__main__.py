"""mxlint CLI.

    python -m tools.mxlint [paths...] [--format=text|json] [--changed]

Exit status: 0 clean, 1 findings (or unparseable files), 2 usage/internal
error.  ``--changed`` lints only the .py files reported by
``git diff --name-only HEAD`` plus untracked files — the pre-commit mode.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from . import lint_paths


def _git_lines(cmd):
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             check=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        raise SystemExit(f"mxlint: --changed needs git: {e}")
    return [line for line in out.splitlines() if line.strip()]


def _changed_files():
    files = set()
    # -M forces rename detection even when the repo config disables it:
    # a renamed-then-edited file must be linted at its NEW path, which
    # plain --name-only reports as a delete+add of the old name only
    # when similarity detection is off. --name-status lines look like
    # "M\tpath", "R100\told\tnew", "C75\tsrc\tdst" — the LAST field is
    # always the path that exists now; D rows have no current path.
    for line in _git_lines(["git", "diff", "-M", "--name-status", "HEAD"]):
        parts = line.split("\t")
        status = parts[0].strip()
        if not status or status.startswith("D") or len(parts) < 2:
            continue
        files.add(parts[-1].strip())
    for line in _git_lines(["git", "ls-files", "--others",
                            "--exclude-standard"]):
        files.add(line.strip())
    return sorted(f for f in files
                  if f.endswith(".py") and os.path.exists(f))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxlint",
        description="trace-safety / concurrency / env-hygiene linter")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: "
                         "incubator_mxnet_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs HEAD (plus untracked)")
    args = ap.parse_args(argv)

    if args.changed:
        paths = _changed_files()
        if not paths:
            if args.format == "json":
                print(json.dumps({"version": 1, "files_scanned": 0,
                                  "findings": [], "suppressed": [],
                                  "errors": [], "counts": {}}))
            else:
                print("mxlint: no changed python files")
            return 0
    else:
        paths = args.paths or ["incubator_mxnet_tpu"]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"mxlint: no such path: {', '.join(missing)}",
                  file=sys.stderr)
            return 2

    result = lint_paths(paths)

    if args.format == "json":
        print(json.dumps(result.as_dict(), indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.render())
        for path, msg in result.errors:
            print(f"{path}: parse error: {msg}")
        n, s = len(result.findings), len(result.suppressed)
        print(f"mxlint: {result.files_scanned} files, {n} finding"
              f"{'' if n == 1 else 's'}, {s} suppressed")
        if s:
            for f in result.suppressed:
                print(f"  suppressed {f.rule} at {f.path}:{f.line} "
                      f"({f.suppress_reason})")
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
