#!/usr/bin/env python
"""Parse training logs into a table (reference tools/parse_log.py: extracts
epoch train/val accuracy and speed from Module.fit/Speedometer output)."""
from __future__ import annotations

import argparse
import re
import sys

EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\].*?(Train|Validation)-([\w-]+)=([0-9.eE+-]+)")
SPEED = re.compile(r"Epoch\[(\d+)\].*?Speed:\s*([0-9.]+)\s*samples/sec")


def parse(lines):
    rows = {}
    for line in lines:
        m = EPOCH_METRIC.search(line)
        if m:
            ep, kind, metric, val = int(m.group(1)), m.group(2), \
                m.group(3), float(m.group(4))
            rows.setdefault(ep, {})[f"{kind.lower()}-{metric}"] = val
        m = SPEED.search(line)
        if m:
            ep, sp = int(m.group(1)), float(m.group(2))
            r = rows.setdefault(ep, {})
            r["speed"] = max(r.get("speed", 0.0), sp)
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile", help="training log ('-' for stdin)")
    ap.add_argument("--format", choices=["table", "markdown", "csv"],
                    default="table")
    args = ap.parse_args()
    f = sys.stdin if args.logfile == "-" else open(args.logfile)
    rows = parse(f)
    if args.logfile != "-":
        f.close()
    if not rows:
        print("no epoch records found", file=sys.stderr)
        return 1
    cols = sorted({k for r in rows.values() for k in r})
    sep = {"table": "  ", "markdown": " | ", "csv": ","}[args.format]
    header = sep.join(["epoch"] + cols)
    if args.format == "markdown":
        header = "| " + header + " |"
    print(header)
    if args.format == "markdown":
        print("|" + "|".join(["---"] * (len(cols) + 1)) + "|")
    for ep in sorted(rows):
        vals = [f"{rows[ep][c]:.6g}" if c in rows[ep] else "" for c in cols]
        line = sep.join([str(ep)] + vals)
        print("| " + line + " |" if args.format == "markdown" else line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
