#!/usr/bin/env python
"""KVStore allreduce bandwidth harness.

Reference: tools/bandwidth/measure.py — times push+pull of ResNet-sized
gradient arrays through the kvstore and reports GB/s per round. Here the
comm path is mesh collectives (psum over ICI on TPU, virtual CPU mesh in
tests), so the number reported is the achieved allreduce bandwidth of
`kvstore.pushpull` end to end.

Usage:
  python tools/measure.py [--network resnet50] [--kv-store device]
                          [--rounds 10] [--devices 8]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# layer-gradient size profiles (num arrays x elements), roughly matching the
# reference's --network presets (parameter tensors of each model)
NETWORKS = {
    "alexnet": [(1, 37748736), (1, 16777216), (1, 4096 * 4096), (5, 1 << 20)],
    "resnet50": [(1, 2048 * 1000), (16, 1 << 21), (32, 1 << 19),
                 (53, 1 << 16)],
    "vgg16": [(1, 102760448), (2, 16777216), (13, 1 << 20)],
    "inception-v3": [(1, 2048 * 1000), (40, 1 << 18), (53, 1 << 16)],
}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="resnet50", choices=sorted(NETWORKS))
    ap.add_argument("--kv-store", default="device",
                    choices=["local", "device", "tpu"])
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--devices", type=int, default=0,
                    help="force a virtual CPU mesh of this many devices")
    ap.add_argument("--dtype", default="float32")
    args = ap.parse_args(argv)

    if args.devices:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_count="
                                   f"{args.devices}").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np
    import incubator_mxnet_tpu as mx

    n_dev = len(jax.devices())
    kv = mx.kv.create(args.kv_store)
    shapes = NETWORKS[args.network]
    keys, sizes = [], []
    k = 0
    for count, elems in shapes:
        for _ in range(count):
            keys.append(str(k))
            sizes.append(elems)
            k += 1
    total_bytes = sum(sizes) * np.dtype(args.dtype).itemsize
    print(f"[measure] {args.network}: {len(keys)} arrays, "
          f"{total_bytes / 1e9:.3f} GB per round, {n_dev} devices, "
          f"kvstore={args.kv_store}", file=sys.stderr)

    vals = {}
    for key, n in zip(keys, sizes):
        arr = mx.nd.array(np.random.uniform(-1, 1, n).astype(args.dtype))
        kv.init(key, arr)
        vals[key] = arr

    outs = {key: mx.nd.zeros((n,), dtype=args.dtype)
            for key, n in zip(keys, sizes)}

    def round_trip():
        for key in keys:
            kv.push(key, vals[key])
        for key in keys:
            kv.pull(key, out=outs[key])
        for o in outs.values():
            o.wait_to_read()

    round_trip()  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(args.rounds):
        round_trip()
    dt = time.perf_counter() - t0

    per_round = dt / args.rounds
    gbps = total_bytes / per_round / 1e9
    print(f"[measure] {per_round * 1e3:.2f} ms/round  "
          f"{gbps:.2f} GB/s effective", file=sys.stderr)
    import json
    print(json.dumps({"metric": f"kvstore_{args.kv_store}_bandwidth",
                      "network": args.network, "value": round(gbps, 3),
                      "unit": "GB/s", "ms_per_round": round(per_round * 1e3, 2),
                      "devices": n_dev}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
