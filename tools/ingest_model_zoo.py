#!/usr/bin/env python
"""Ingest reference model-zoo weights and capture forward-activation
goldens (VERDICT r4 item 8: make pretrained parity testable-on-arrival).

The reference publishes its zoo artifacts by sha1
(python/mxnet/gluon/model_zoo/model_store.py:40 — the same table ships in
incubator_mxnet_tpu.gluon.model_zoo.model_store). This build is
zero-egress, so the script takes EITHER a real repo URL (the day egress
exists) or a file:// mirror, then for every requested model:

  1. fetches + sha1-verifies `<name>-<hash8>.params` through
     get_model_file (the store's own cache/corruption machinery),
  2. loads the reference-trained tensors into the TPU-native zoo net via
     the role-mapping loader (compat.load_reference_parameters),
  3. runs a DETERMINISTIC forward probe and writes
     tests/fixtures/zoo_goldens/<name>.npz (probe seed/shape + logits).

tests/test_zoo_goldens.py replays every golden found there on each test
run — so the moment fixtures exist, pretrained parity becomes a
regression test, with no code changes.

Usage:
  python tools/ingest_model_zoo.py --repo file:///mnt/mirror --models all
  python tools/ingest_model_zoo.py --models resnet50_v1,vgg16
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

PROBE_SEED = 20260731
PROBE_BATCH = 2


def probe_shape(name):
    """Input resolution per family (inception takes 299, everything else
    the ImageNet-standard 224 — reference model_zoo docstrings)."""
    side = 299 if "inception" in name else 224
    return (PROBE_BATCH, 3, side, side)


def probe_input(name):
    rng = np.random.RandomState(PROBE_SEED)
    return rng.rand(*probe_shape(name)).astype(np.float32)


def ingest(models, out_dir, root=None):
    """Fetch, convert, and capture goldens. Returns {name: npz_path}."""
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.model_zoo import (
        get_model_file, load_reference_parameters, model_store)
    from incubator_mxnet_tpu.gluon.model_zoo.vision import get_model

    os.makedirs(out_dir, exist_ok=True)
    written = {}
    for name in models:
        params_path = get_model_file(name, root=root)
        net = get_model(name, pretrained=False)
        load_reference_parameters(net, params_path)
        x = probe_input(name)
        logits = net(nd.array(x)).asnumpy().astype(np.float32)
        out_path = os.path.join(out_dir, f"{name}.npz")
        np.savez(
            out_path,
            logits=logits,
            probe_seed=np.int64(PROBE_SEED),
            probe_shape=np.asarray(probe_shape(name), np.int64),
            sha1=np.bytes_(model_store._SHA1[name].encode()),
        )
        written[name] = out_path
        print(f"[ingest] {name}: goldens -> {out_path} "
              f"(logits {logits.shape}, sha1 {model_store._SHA1[name][:8]})")
    return written


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", default="all",
                    help="comma list, or 'all' for the full sha1 table")
    ap.add_argument("--repo", default=None,
                    help="model repo URL (file:// mirror works); sets "
                         "MXNET_GLUON_REPO for the fetch")
    ap.add_argument("--root", default=None,
                    help="params cache dir (default ~/.mxnet/models)")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..",
        "tests", "fixtures", "zoo_goldens"))
    args = ap.parse_args()

    if args.repo:
        os.environ["MXNET_GLUON_REPO"] = args.repo
    from incubator_mxnet_tpu.gluon.model_zoo import model_store
    models = (sorted(model_store._SHA1) if args.models == "all"
              else [m.strip() for m in args.models.split(",") if m.strip()])
    ok, failed = [], []
    for name in models:
        try:
            ingest([name], args.out, root=args.root)
            ok.append(name)
        except Exception as e:   # keep going: a 404 on one artifact must
            failed.append(name)  # not lose the other 34 goldens
            print(f"[ingest] {name}: FAILED {e!r}", file=sys.stderr)
    print(f"[ingest] done: {len(ok)} captured, {len(failed)} failed"
          + (f" ({','.join(failed)})" if failed else ""))
    return 1 if failed and not ok else 0


if __name__ == "__main__":
    sys.exit(main())
