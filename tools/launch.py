#!/usr/bin/env python
"""Cluster launcher (reference tools/launch.py:71-121, which delegates to
dmlc_tracker's ssh/mpi/sge/yarn/local modes and wires the DMLC_* env
protocol for ps-lite).

TPU-native redesign: there is no scheduler/server role — every process is a
peer in the jax distributed runtime. The launcher starts N worker processes
(locally or over ssh), giving each the JAX coordination env:

    JAX_COORDINATOR_ADDRESS  host:port of process 0
    JAX_NUM_PROCESSES        n
    JAX_PROCESS_ID           0..n-1

plus the framework's own MXTPU_* mirrors, then waits. Inside the program,
`incubator_mxnet_tpu.kvstore.create("tpu")` picks rank/size from the jax
runtime, so reference-style `launch.py -n 4 python train.py --kv-store tpu`
keeps its shape.

Usage:
    python tools/launch.py -n 4 python train_mnist.py --kv-store tpu
    python tools/launch.py -n 8 -H hostfile --launcher ssh python train.py
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def parse_hostfile(path):
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line.split()[0])
    if not hosts:
        raise SystemExit(f"hostfile {path} has no hosts")
    return hosts


def worker_env(base, i, n, coordinator):
    env = dict(base)
    env.update({
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "JAX_NUM_PROCESSES": str(n),
        "JAX_PROCESS_ID": str(i),
        "MXTPU_NUM_WORKERS": str(n),
        "MXTPU_WORKER_ID": str(i),
        # reference protocol mirrors so ported scripts reading DMLC_* work
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(i),
        "DMLC_ROLE": "worker",
    })
    return env


def launch_local(n, cmd, coordinator):
    procs = []
    try:
        for i in range(n):
            procs.append(subprocess.Popen(
                cmd, env=worker_env(os.environ, i, n, coordinator)))
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        return 130


def launch_ssh(n, hosts, cmd, coordinator, user=None):
    """One worker per host round-robin; assumes passwordless ssh + synced
    working directory (same contract as the reference's ssh tracker)."""
    procs = []
    cwd = os.getcwd()
    for i in range(n):
        host = hosts[i % len(hosts)]
        target = f"{user}@{host}" if user else host
        envs = " ".join(f"{k}={v!r}" for k, v in
                        worker_env({}, i, n, coordinator).items())
        remote = f"cd {cwd} && env {envs} " + " ".join(cmd)
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no",
                                       target, remote]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main():
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("--coordinator", default="127.0.0.1:43219",
                    help="host:port of process 0's coordination service")
    ap.add_argument("--user", default=None, help="ssh user")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    cmd = args.command[1:] if args.command[0] == "--" else args.command

    if args.launcher == "ssh":
        if not args.hostfile:
            ap.error("--launcher ssh requires -H hostfile")
        hosts = parse_hostfile(args.hostfile)
        coord = args.coordinator
        if coord.startswith("127."):
            coord = f"{hosts[0]}:{coord.rsplit(':', 1)[1]}"
        return launch_ssh(args.num_workers, hosts, cmd, coord, args.user)
    return launch_local(args.num_workers, cmd, args.coordinator)


if __name__ == "__main__":
    sys.exit(main())
