"""Registry waivers for graph-anchored shardlint findings.

A finding that anchors to a source line is silenced in place with
``# shardlint: disable=RULE(reason)``; a finding that judges a whole
capture (or anchors into generated/corpus code) has no natural line to
comment, so it is waived here: (rule, capture-key glob, reason).

Rules of the registry:
  * every entry carries a reason — an empty reason is a test failure;
  * the list is BUDGETED: tests/test_shardlint.py pins the exact
    entries and caps the count at 10, so a waiver is a reviewed,
    deliberate exception, not a pressure valve.
"""

WAIVERS = [
    # bf16 training intentionally upcasts the loss to an f32 master
    # accumulation (mixed-precision policy, docs/architecture/
    # note_static_analysis.md); the upcast is the point, not a leak.
    ("SL02", "trainstep:*",
     "bf16 training keeps the loss in f32 master precision by design"),
]
