"""shardlint rule passes SL01-SL05 over Capture records.

Each pass is a function `check_slNN(cap) -> [ShardFinding]` walking the
captured jaxpr (or partition metadata) — never re-tracing, never
compiling.  The jaxpr walker recurses into sub-jaxprs (pjit, cond
branches, scan bodies) by duck typing on eqn params, so a callback
buried three jit levels down still surfaces with its user source line.

mxlint's AST rules see what the *author wrote*; these see what XLA will
actually *run* — the two catch disjoint bug families (a
`jnp.float64` cast is trace-safe Python and invisible to TS01-TS04,
but it doubles every downstream buffer on a backend that honors x64).
"""
from __future__ import annotations

__all__ = ["check_capture", "walk_eqns", "source_anchor"]

# non-donatable argument roles: gradients are re-used by the next
# backward pass, shared weights outlive the call
_NEVER_DONATE = ("grads", "weights_shared")
# roles the donation audit expects to see donated when the backend
# supports buffer aliasing
_DONATE_ELIGIBLE = ("params", "opt_state", "weights")
# host-callback primitives: each one stalls the TPU step on a host
# round-trip (debug_callback backs jax.debug.print)
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")


# ---------------------------------------------------------------------------
# jaxpr plumbing
# ---------------------------------------------------------------------------

def _sub_jaxprs(params):
    """Yield inner jaxprs hiding in eqn params (pjit: ClosedJaxpr under
    'jaxpr'; cond: tuple of branches; scan/while: body jaxprs)."""
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for item in vs:
            inner = getattr(item, "jaxpr", None)   # ClosedJaxpr
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(item, "eqns"):            # raw Jaxpr
                yield item


def walk_eqns(jaxpr):
    """Depth-first over every eqn including sub-jaxprs. Accepts a
    ClosedJaxpr or Jaxpr."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in getattr(jaxpr, "eqns", ()):
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from walk_eqns(sub)


def source_anchor(eqn):
    """(path, line) of the user frame that staged this eqn, or
    (None, None). Uses jax's private source_info_util behind a broad
    guard — anchors are a nicety, findings survive without them."""
    try:
        si = getattr(eqn, "source_info", None)
        if si is None:
            return None, None
        from jax._src import source_info_util as siu
        frame = siu.user_frame(si)
        if frame is None:
            return None, None
        return (getattr(frame, "file_name", None),
                getattr(frame, "start_line", None) or None)
    except Exception:       # noqa: BLE001 — private API, version drift
        return None, None


def _dtype_of(var):
    aval = getattr(var, "aval", None)
    dt = getattr(aval, "dtype", None)
    return str(dt) if dt is not None else None


def _finding(cap, rule, message, eqn=None):
    from . import ShardFinding
    path, line = source_anchor(eqn) if eqn is not None else (None, None)
    return ShardFinding(rule, cap.key, message, path=path, line=line)


# ---------------------------------------------------------------------------
# SL01 — host callback in a jitted program
# ---------------------------------------------------------------------------

def check_sl01(cap):
    if cap.jaxpr is None:
        return []
    out = []
    for eqn in walk_eqns(cap.jaxpr):
        name = eqn.primitive.name
        if name in _CALLBACK_PRIMS:
            what = ("jax.debug.print/debug_callback"
                    if name == "debug_callback" else name)
            out.append(_finding(
                cap, "SL01",
                f"{what} staged inside jitted program — every step "
                f"round-trips to the host", eqn=eqn))
    return out


# ---------------------------------------------------------------------------
# SL02 — f64 promotion / silent bf16 upcast
# ---------------------------------------------------------------------------

def check_sl02(cap):
    if cap.jaxpr is None:
        return []
    out = []
    for eqn in walk_eqns(cap.jaxpr):
        in_dts = [_dtype_of(v) for v in eqn.invars]
        out_dts = [_dtype_of(v) for v in eqn.outvars]
        if "float64" in out_dts and "float64" not in in_dts:
            out.append(_finding(
                cap, "SL02",
                f"{eqn.primitive.name} introduces float64 from "
                f"{[d for d in in_dts if d]} inputs", eqn=eqn))
        elif (cap.declared_bf16
              and eqn.primitive.name == "convert_element_type"
              and "bfloat16" in in_dts
              and str(eqn.params.get("new_dtype")) == "float32"):
            out.append(_finding(
                cap, "SL02",
                "bfloat16 value upcast to float32 inside a "
                "declared-bf16 program", eqn=eqn))
    return out


# ---------------------------------------------------------------------------
# SL03 — donation audit
# ---------------------------------------------------------------------------

def check_sl03(cap):
    """Judge donate_argnums against the call site's declared arg roles.
    Captures without arg_roles are skipped outright — SL03 never
    speculates about what an un-annotated argument means."""
    roles = cap.arg_roles
    if roles is None:
        return []
    donated = set(cap.donate_argnums)
    out = []
    bad = sorted(i for i in donated
                 if roles.get(i) in _NEVER_DONATE)
    if bad:
        out.append(_finding(
            cap, "SL03",
            f"non-donatable args donated: "
            f"{[(i, roles[i]) for i in bad]} — the caller reuses these "
            f"buffers after the call"))
    if donated and not cap.donation_supported:
        out.append(_finding(
            cap, "SL03",
            f"donation requested ({sorted(donated)}) but backend "
            f"{cap.backend!r} does not alias buffers — gate on "
            f"_donation_supported()"))
    if cap.donation_supported:
        missed = sorted(i for i, r in roles.items()
                        if r in _DONATE_ELIGIBLE and i not in donated)
        if missed:
            out.append(_finding(
                cap, "SL03",
                f"donation-eligible args not donated: "
                f"{[(i, roles[i]) for i in missed]} — each one doubles "
                f"its buffer's HBM footprint across the update"))
    return out


# ---------------------------------------------------------------------------
# SL04 — partition-rule coverage
# ---------------------------------------------------------------------------

def check_sl04(cap):
    out = []
    for leaf in cap.meta.get("unmatched", ()):
        out.append(_finding(
            cap, "SL04",
            f"param {leaf!r} matched no partition rule and fell back "
            f"to full replication"))
    return out


# ---------------------------------------------------------------------------
# SL05 — implicit transfer / resharding
# ---------------------------------------------------------------------------

def check_sl05(cap):
    out = []
    if cap.jaxpr is not None:
        last_constraint = {}     # outvar id -> (eqn, sharding repr)
        for eqn in walk_eqns(cap.jaxpr):
            name = eqn.primitive.name
            if name == "device_put":
                out.append(_finding(
                    cap, "SL05",
                    "device_put staged inside jitted program — an "
                    "implicit transfer XLA cannot schedule around",
                    eqn=eqn))
            elif name == "sharding_constraint":
                sh = repr(eqn.params.get("sharding"))
                for v in eqn.invars:
                    prev = last_constraint.get(id(v))
                    if prev is not None and prev[1] != sh:
                        out.append(_finding(
                            cap, "SL05",
                            f"value resharded back-to-back: "
                            f"{prev[1]} then {sh} — the first "
                            f"constraint only buys a transfer",
                            eqn=eqn))
                for v in eqn.outvars:
                    last_constraint[id(v)] = (eqn, sh)
    if cap.lowered_text and cap.allgather_budget is not None:
        n = cap.lowered_text.count("all-gather")
        if n > cap.allgather_budget:
            out.append(_finding(
                cap, "SL05",
                f"lowered module contains {n} all-gathers, over the "
                f"declared budget of {cap.allgather_budget}"))
    return out


_PASSES = (check_sl01, check_sl02, check_sl03, check_sl04, check_sl05)


def check_capture(cap):
    """All findings for one Capture. A pass that crashes on an exotic
    jaxpr records an analyzer error finding rather than killing the
    run — raising here would make the linter flakier than the code it
    lints."""
    findings, errors = [], []
    for p in _PASSES:
        try:
            findings.extend(p(cap))
        except Exception as e:  # noqa: BLE001 — survive exotic jaxprs
            errors.append((cap.key, f"{p.__name__}: {e!r}"))
    return findings, errors
