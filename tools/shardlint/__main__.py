"""shardlint CLI.

    python -m tools.shardlint [--corpus NAMES] [--fixture FILE]
                              [--format=text|json] [--list] [--no-waivers]

Default mode traces the registered model corpus (tools/shardlint/
corpus.py) on CPU and analyzes the captures against the in-tree waiver
registry. ``--fixture FILE`` analyzes a fixture module's ``build()``
captures instead (its own ``WAIVERS`` attribute applies, if any).

Exit status: 0 clean, 1 findings or corpus/analyzer errors, 2 usage
error.  ``MXNET_SHARDLINT_CORPUS`` (comma-separated names) preselects
corpus entries when --corpus is not given.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# the corpus must trace, never touch a real accelerator: an operator
# running the linter on a TPU host must not grab the chips
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from . import analyze, load_fixture     # noqa: E402
from . import corpus as _corpus         # noqa: E402


def _render_text(result):
    for f in result.findings:
        print(f.render())
    for key, msg in result.errors:
        print(f"[{key}]: error: {msg}")
    n, s, w = (len(result.findings), len(result.suppressed),
               len(result.waived))
    print(f"shardlint: {result.captures_analyzed} captures, {n} finding"
          f"{'' if n == 1 else 's'}, {s} suppressed, {w} waived")
    for f in result.suppressed:
        print(f"  suppressed {f.rule} at {f.path}:{f.line} "
              f"({f.suppress_reason})")
    for f in result.waived:
        print(f"  waived {f.rule} on {f.key} ({f.waive_reason})")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="shardlint",
        description="jaxpr/HLO-level sharding & performance analyzer")
    ap.add_argument("--corpus", default=None,
                    help="comma-separated corpus entries (default: all; "
                         "env MXNET_SHARDLINT_CORPUS also selects)")
    ap.add_argument("--fixture", default=None,
                    help="analyze a fixture module's build() captures "
                         "instead of the corpus")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list", action="store_true",
                    help="list corpus entries and rules, then exit")
    ap.add_argument("--no-waivers", action="store_true",
                    help="judge with the waiver registry disabled")
    args = ap.parse_args(argv)

    if args.list:
        from . import RULES
        from .waivers import WAIVERS
        print("corpus entries:")
        for name, fn in _corpus.entries().items():
            doc = (fn.__doc__ or "").strip().splitlines()[0]
            print(f"  {name}: {doc}")
        print("rules:")
        for rule, (title, _hint) in sorted(RULES.items()):
            print(f"  {rule}: {title}")
        print(f"waivers: {len(WAIVERS)}")
        for rule, glob, reason in WAIVERS:
            print(f"  {rule} on {glob}: {reason}")
        return 0

    if args.fixture is not None:
        if not os.path.exists(args.fixture):
            print(f"shardlint: no such fixture: {args.fixture}",
                  file=sys.stderr)
            return 2
        captures, fixture_waivers = load_fixture(args.fixture)
        waivers = () if args.no_waivers else fixture_waivers
        result = analyze(captures, waivers=waivers)
    else:
        names = args.corpus if args.corpus is not None else \
            os.environ.get("MXNET_SHARDLINT_CORPUS", "")
        names = [n.strip() for n in names.split(",") if n.strip()] or None
        try:
            captures, errors = _corpus.run(names)
        except KeyError as e:
            print(f"shardlint: {e.args[0]}", file=sys.stderr)
            return 2
        result = analyze(captures,
                         waivers=() if args.no_waivers else None)
        result.errors.extend(("corpus:" + name, msg)
                             for name, msg in errors)

    if args.format == "json":
        print(json.dumps(result.as_dict(), indent=2, sort_keys=True))
    else:
        _render_text(result)
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
