"""The offline model corpus: the package's own train/serve/parallel
entry points, registered so `python -m tools.shardlint` can judge them
without a TPU and without a training run.

Each entry is a builder that drives a real framework path with capture
forced on; the captures land in the package-side registry
(incubator_mxnet_tpu.shardlint) and `run()` hands them back for
analysis. Entries trace on CPU and avoid XLA compiles where the
framework offers a trace-only path (TrainStep.trace_for_analysis,
_CachedJit.trace_signature) — the serve entry pays one tiny MLP
compile because the predictor's graph only exists per bucket.

This corpus is the tier-1 gate's ground truth: tests/test_shardlint.py
asserts the whole thing analyzes clean against the exact waiver list in
waivers.py.
"""
from __future__ import annotations

from collections import OrderedDict

__all__ = ["entries", "run"]


def _corpus_train_step():
    """Plain f32 TrainStep over a 1+-device mesh: donation gating,
    partition declaration, and the full fused step jaxpr."""
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.parallel import TrainStep, make_mesh
    import jax.numpy as jnp

    net = nn.Dense(4, in_units=8)
    net.initialize()

    def loss_fn(out, label):
        return jnp.mean((out - label) ** 2)

    # the batch's leading dim must divide the data axis whatever the
    # device count is (1 standalone, 8 under the test harness's forced
    # host-platform device count)
    import jax
    b = 8 * max(len(jax.devices()), 1)
    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05},
                     mesh=make_mesh(),
                     example_inputs=[nd.array(np.ones((b, 8), np.float32))])
    step.trace_for_analysis(nd.array(np.ones((b, 8), np.float32)),
                            nd.array(np.ones((b, 4), np.float32)))


def _corpus_train_bf16():
    """bf16 TrainStep whose loss deliberately upcasts to an f32 master
    accumulation — the intentional SL02 hit the waiver registry carries
    (the waiver demo must stay deterministic, so do not 'fix' this)."""
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.parallel import TrainStep
    import jax.numpy as jnp

    net = nn.Dense(4, in_units=8)
    net.initialize()

    def loss_fn(out, label):
        return jnp.mean((out.astype(jnp.float32) - label) ** 2)

    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05},
                     dtype=jnp.bfloat16,
                     example_inputs=[nd.array(np.ones((4, 8), np.float32))])
    step.trace_for_analysis(nd.array(np.ones((4, 8), np.float32)),
                            nd.array(np.ones((4, 4), np.float32)))


def _corpus_serve_predict():
    """Export a tiny MLP, reload through Predictor.from_artifact, run one
    predict — the serving execute path's capture."""
    import os
    import tempfile
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.serve import Predictor

    net = nn.HybridSequential()
    net.add(nn.Dense(5, activation="relu"), nn.Dense(3))
    net.initialize()
    net(nd.array(np.zeros((1, 6), np.float32)))
    d = tempfile.mkdtemp(prefix="shardlint_corpus_")
    path = os.path.join(d, "model")
    net.export(path)
    pred = Predictor.from_artifact(path, bucket_sizes=(2,))
    pred.predict({"data": np.ones((2, 6), np.float32)})


def _corpus_fused_optimizer():
    """The fused multi-tensor optimizer executable (role-annotated in
    _fused_fn), traced without compiling."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops import optimizer_ops as _oo

    f = _oo._fused_fn("sgd_mom_update", 2, 3, (("momentum", 0.9),),
                      ("lr", "wd"))
    dyn = (jnp.full((2,), 0.1, jnp.float32),
           jnp.zeros((2,), jnp.float32))
    flat = [jnp.ones((4,), jnp.float32) for _ in range(6)]
    f.trace_signature(dyn, jnp.float32(1.0), *flat)


def _corpus_partition_rules():
    """The in-tree Megatron rules table (tensor_parallel.
    transformer_partition_rules) over transformer-style param names —
    the SL04 coverage capture proving the table is total."""
    import numpy as np
    from incubator_mxnet_tpu.parallel import (match_partition_rules,
                                              transformer_partition_rules)

    params = {
        "embed": np.zeros((32, 16), np.float32),
        "pos_embed": np.zeros((8, 16), np.float32),
        "l0.wq": np.zeros((16, 16), np.float32),
        "l0.wo": np.zeros((16, 16), np.float32),
        "l0.w_in": np.zeros((16, 64), np.float32),
        "l0.w_out": np.zeros((64, 16), np.float32),
        "l0.ln1_g": np.zeros((16,), np.float32),
        "global_step": np.zeros((), np.float32),
    }
    match_partition_rules(transformer_partition_rules(), params,
                          on_unmatched="error",
                          key="corpus:partition_rules")


def _corpus_composed_1f1b():
    """The flagship composed-parallel train step on a real multi-axis
    mesh with the 1F1B pipeline backward, bf16-declared and all-gather
    budgeted — traced via the cached_jit signature path (no compile).
    This is the program the pipeline custom_vjp lives in, so SL03
    donation and SL05 resharding judge the hand-written backward too."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.parallel import make_mesh
    from incubator_mxnet_tpu.models.composed import (ComposedConfig,
                                                     ComposedPipelineLM)

    n = len(jax.devices())
    if n >= 8 and n % 8 == 0:
        axes = {"dp": n // 4, "pp": 2, "tp": 2}
    elif n >= 2 and n % 2 == 0:
        axes = {"dp": n // 2, "pp": 2}
    else:
        return      # single device: no pipeline axis to judge
    cfg = ComposedConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=2,
                         d_ff=32, n_experts=2, moe_every=1,
                         capacity_factor=2.0, max_len=32, dtype="bfloat16")
    model = ComposedPipelineLM(cfg)
    mesh = make_mesh(axes)
    params = model.init_params(jax.random.PRNGKey(0), axes["pp"])
    step, shard_params, init_opt = model.make_train_step(
        mesh, n_microbatches=2, schedule="1f1b", remat="dots_saveable")
    p = shard_params(params)
    rng = np.random.RandomState(0)
    B = 4 * axes["dp"]
    tokens = jnp.asarray(rng.randint(0, 32, (B, 8)).astype(np.int32))
    targets = jnp.asarray(rng.randint(0, 32, (B, 8)).astype(np.int32))
    step._cached.trace_signature(p, init_opt(p), tokens, targets, 0)


def _corpus_composed_zb1():
    """The ZB-H1 zero-bubble composed step: backward split into B/W
    half-passes with parked-cotangent rings inside the custom_vjp — the
    most schedule-dense program in the repo, traced via the cached_jit
    signature path (no compile) so SL02 bf16 policy, SL03 donation,
    SL04 all-gather budget and SL05 resharding judge the split backward
    the same way they judge the fused one."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.parallel import make_mesh
    from incubator_mxnet_tpu.models.composed import (ComposedConfig,
                                                     ComposedPipelineLM)

    n = len(jax.devices())
    if n >= 8 and n % 8 == 0:
        axes = {"dp": n // 4, "pp": 2, "tp": 2}
    elif n >= 2 and n % 2 == 0:
        axes = {"dp": n // 2, "pp": 2}
    else:
        return      # single device: no pipeline axis to judge
    cfg = ComposedConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=2,
                         d_ff=32, n_experts=2, moe_every=1,
                         capacity_factor=2.0, max_len=32, dtype="bfloat16")
    model = ComposedPipelineLM(cfg)
    mesh = make_mesh(axes)
    params = model.init_params(jax.random.PRNGKey(0), axes["pp"])
    step, shard_params, init_opt = model.make_train_step(
        mesh, n_microbatches=4, schedule="zb1", remat="none")
    p = shard_params(params)
    rng = np.random.RandomState(0)
    B = 4 * axes["dp"]
    tokens = jnp.asarray(rng.randint(0, 32, (B, 8)).astype(np.int32))
    targets = jnp.asarray(rng.randint(0, 32, (B, 8)).astype(np.int32))
    step._cached.trace_signature(p, init_opt(p), tokens, targets, 0)


def _corpus_disagg_prefill_chunk():
    """The disaggregated-serving chunked-prefill executable
    (serve/disagg.PrefillPredictor): scatter-into-pages + full-window
    paged attention with traced start/length offsets, traced via the
    cached_jit signature path (no compile)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.serve.decode import DecodePredictor
    from incubator_mxnet_tpu.serve.disagg import PrefillPredictor

    V, H, D = 32, 2, 8
    E = H * D
    rng = np.random.RandomState(0)
    params = {"emb": rng.randn(V, E).astype(np.float32),
              "wq": rng.randn(E, E).astype(np.float32),
              "wk": rng.randn(E, E).astype(np.float32),
              "wv": rng.randn(E, E).astype(np.float32),
              "wo": rng.randn(E, E).astype(np.float32),
              "w_out": rng.randn(E, V).astype(np.float32)}
    pred = DecodePredictor(params, num_heads=H, head_dim=D, vocab=V,
                           page_size=4, num_pages=16, slots=2,
                           max_pages_per_seq=4, prompt_buckets=(4, 8))
    chunker = PrefillPredictor(pred, chunk=8)
    i32 = jnp.int32
    kv = jax.ShapeDtypeStruct((pred.num_pages, pred.page_size,
                               pred.num_heads, pred.head_dim), jnp.float32)
    chunker._exec_chunk().trace_signature(
        pred._param_vals,
        jax.ShapeDtypeStruct((1, chunker.chunk), i32),
        jax.ShapeDtypeStruct((), i32), jax.ShapeDtypeStruct((), i32),
        kv, kv, jax.ShapeDtypeStruct((pred.max_pages_per_seq,), i32))


def _corpus_spec_verify():
    """The speculative-decoding batched-verify executable
    (serve/spec_decode.SpecDecoder): slots x G token/position blocks
    scattered into the paged pool + multi-query paged attention over
    per-row windows, traced via the cached_jit signature path (no
    compile)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.serve.decode import DecodePredictor
    from incubator_mxnet_tpu.serve.spec_decode import SpecDecoder

    V, H, D = 32, 2, 8
    E = H * D
    rng = np.random.RandomState(0)
    params = {"emb": rng.randn(V, E).astype(np.float32),
              "wq": rng.randn(E, E).astype(np.float32),
              "wk": rng.randn(E, E).astype(np.float32),
              "wv": rng.randn(E, E).astype(np.float32),
              "wo": rng.randn(E, E).astype(np.float32),
              "w_out": rng.randn(E, V).astype(np.float32)}
    pred = DecodePredictor(params, num_heads=H, head_dim=D, vocab=V,
                           page_size=4, num_pages=16, slots=2,
                           max_pages_per_seq=4, prompt_buckets=(4, 8))
    spec = SpecDecoder(pred, k=3)
    i32 = jnp.int32
    kv = jax.ShapeDtypeStruct((pred.num_pages, pred.page_size,
                               pred.num_heads, pred.head_dim), jnp.float32)
    sg = jax.ShapeDtypeStruct((pred.slots, spec.width), i32)
    spec._exec_verify().trace_signature(
        pred._param_vals, sg, sg, kv, kv,
        jax.ShapeDtypeStruct((pred.slots, pred.max_pages_per_seq), i32))


def entries():
    """name -> builder, in run order."""
    return OrderedDict([
        ("train_step", _corpus_train_step),
        ("train_bf16", _corpus_train_bf16),
        ("serve_predict", _corpus_serve_predict),
        ("fused_optimizer", _corpus_fused_optimizer),
        ("partition_rules", _corpus_partition_rules),
        ("composed_1f1b", _corpus_composed_1f1b),
        ("composed_zb1", _corpus_composed_zb1),
        ("disagg_prefill_chunk", _corpus_disagg_prefill_chunk),
        ("spec_verify", _corpus_spec_verify),
    ])


def run(names=None):
    """Drive the corpus with capture forced on. Returns
    (captures, errors): the Capture list recorded across the selected
    entries, and (entry, message) pairs for builders that raised. The
    process's prior capture state (enabled flag, buffer) is restored on
    exit so running the corpus inside a test session leaks nothing."""
    from incubator_mxnet_tpu import shardlint as sl
    table = entries()
    unknown = [n for n in (names or ()) if n not in table]
    if unknown:
        raise KeyError(f"unknown corpus entries {unknown}; "
                       f"have {list(table)}")
    selected = [(n, table[n]) for n in (names or table)]
    errors = []
    prev_enabled = sl.enable(True)
    prev_captures = sl.captures()
    sl.clear()
    try:
        for name, builder in selected:
            try:
                builder()
            except Exception as e:    # noqa: BLE001 — report, keep going
                errors.append((name, f"{type(e).__name__}: {e}"))
        return sl.captures(), errors
    finally:
        sl.clear()
        with sl._lock:
            sl._captures.extend(prev_captures)
        sl.enable(prev_enabled)
