"""shardlint — jaxpr/HLO-level sharding & performance analyzer for
incubator_mxnet_tpu.

Run it offline over the registered model corpus (traces on CPU, never
compiles):

    python -m tools.shardlint [--corpus NAMES] [--format=text|json]

or programmatically over captures the package recorded while
MXNET_SHARDLINT was on:

    from incubator_mxnet_tpu import shardlint as sl
    from tools import shardlint as tsl
    result = tsl.analyze(sl.captures())

mxlint (tools/mxlint) lints the Python the author wrote; shardlint lints
the *lowered program* — the graph XLA will run — so it catches the bug
families AST analysis cannot see: host callbacks staged into a hot step
(SL01), silent f64/bf16 precision drift (SL02), missed or wrong buffer
donation (SL03), params silently falling back to full replication
(SL04), and implicit transfers/resharding churn (SL05).

Two silencing mechanisms, both counted and both requiring a reason:

  * source-anchored findings (a specific eqn with a user frame) honor
    ``# shardlint: disable=RULE(reason)`` on or directly above the line;
  * graph-anchored findings (whole-capture judgements like SL03/SL04)
    have no line to comment — they are waived by (rule, key-glob,
    reason) entries in tools/shardlint/waivers.py.
"""
from __future__ import annotations

import fnmatch
import re

__all__ = ["RULES", "ShardFinding", "ShardlintResult", "analyze",
           "load_fixture"]

# rule id -> (one-line title, fix hint)
RULES = {
    "SL01": (
        "host callback staged in jitted program",
        "drop the callback from the hot path, or keep it behind a debug "
        "flag that stays False in production steps"),
    "SL02": (
        "float64 promotion or bf16 upcast in traced program",
        "pin the dtype (jnp.float32/bfloat16) at the point of creation; "
        "a python float or np.float64 scalar silently widens the chain"),
    "SL03": (
        "buffer donation wrong or missing",
        "donate params/opt-state on aliasing backends "
        "(donate_argnums=...), never donate gradients, and gate the "
        "request on _donation_supported()"),
    "SL04": (
        "param fell back to full replication",
        "add a matching partition rule, or declare replication "
        "explicitly with a ('.*', PartitionSpec()) catch-all"),
    "SL05": (
        "implicit transfer or resharding churn",
        "move device_put outside jit; collapse conflicting "
        "with_sharding_constraint chains; raise the all-gather budget "
        "only with a comment saying why"),
}

_SUPP_ITEM = re.compile(r"([A-Z]{2}\d{2})\(([^)]*)\)")
_SUPP_RE = re.compile(r"#\s*shardlint:\s*disable=")


class ShardFinding:
    """One rule violation against a capture, optionally anchored to the
    user source line that staged the offending eqn."""

    __slots__ = ("rule", "key", "message", "hint", "path", "line",
                 "suppress_reason", "waive_reason")

    def __init__(self, rule, key, message, path=None, line=None):
        self.rule = rule
        self.key = key
        self.message = message
        self.hint = RULES[rule][1]
        self.path = path
        self.line = line
        self.suppress_reason = None
        self.waive_reason = None

    def as_dict(self):
        d = {"rule": self.rule, "key": self.key, "message": self.message,
             "hint": self.hint}
        if self.path is not None:
            d["path"] = self.path
            d["line"] = self.line
        if self.suppress_reason is not None:
            d["suppressed"] = self.suppress_reason
        if self.waive_reason is not None:
            d["waived"] = self.waive_reason
        return d

    def render(self):
        where = (f"{self.path}:{self.line}" if self.path
                 else f"[{self.key}]")
        return (f"{where}: {self.rule} {self.message} (key={self.key})"
                f"\n    hint: {self.hint}")


class ShardlintResult:
    """Findings + silences for one analyze() run."""

    def __init__(self):
        self.findings = []       # active ShardFinding objects
        self.suppressed = []     # silenced by a source disable comment
        self.waived = []         # silenced by a registry waiver
        self.errors = []         # (key, message) pass/corpus failures
        self.captures_analyzed = 0

    @property
    def clean(self):
        return not self.findings and not self.errors

    def as_dict(self):
        counts = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "version": 1,
            "captures": self.captures_analyzed,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [
                {"rule": f.rule, "key": f.key, "path": f.path,
                 "line": f.line, "reason": f.suppress_reason}
                for f in self.suppressed],
            "waived": [
                {"rule": f.rule, "key": f.key, "reason": f.waive_reason}
                for f in self.waived],
            "errors": [{"key": k, "message": m} for k, m in self.errors],
            "counts": counts,
        }


class _SourceSuppressions:
    """Lazy per-file ``# shardlint: disable=RULE(reason)`` lookup.

    shardlint findings anchor to arbitrary user files via jaxpr source
    info, so suppression comments are read from the anchored file on
    demand (cached), not from a pre-parsed module table like mxlint's.
    A disable with an empty reason never suppresses."""

    def __init__(self):
        self._cache = {}         # path -> {line: {rule: reason}}

    def _table(self, path):
        table = self._cache.get(path)
        if table is None:
            table = {}
            try:
                with open(path, "r", encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = []
            for i, line in enumerate(lines, start=1):
                m = _SUPP_RE.search(line)
                if not m:
                    continue
                for rule, reason in _SUPP_ITEM.findall(line[m.end():]):
                    table.setdefault(i, {})[rule] = (
                        reason.strip(), line.lstrip().startswith("#"))
            self._cache[path] = table
        return table

    def lookup(self, rule, path, line):
        if path is None or line is None:
            return None
        table = self._table(path)
        for cand in (line, line - 1):
            entry = table.get(cand, {}).get(rule)
            if entry is None:
                continue
            reason, pure_comment = entry
            if cand == line - 1 and not pure_comment:
                continue
            if reason:
                return reason
        return None


def _waiver_for(finding, waivers):
    for rule, key_glob, reason in waivers:
        if rule == finding.rule and fnmatch.fnmatch(finding.key,
                                                    key_glob):
            return reason
    return None


def analyze(captures, waivers=None):
    """Run SL01-SL05 over `captures` (Capture objects from
    incubator_mxnet_tpu.shardlint). `waivers` is an iterable of
    (rule, key-glob, reason) triples; None means the in-tree registry
    (tools/shardlint/waivers.py). Pass `waivers=()` to judge with no
    silences at all."""
    from .rules import check_capture
    if waivers is None:
        from .waivers import WAIVERS as waivers
    result = ShardlintResult()
    supp = _SourceSuppressions()
    for cap in captures:
        result.captures_analyzed += 1
        findings, errors = check_capture(cap)
        result.errors.extend(errors)
        for f in findings:
            reason = supp.lookup(f.rule, f.path, f.line)
            if reason is not None:
                f.suppress_reason = reason
                result.suppressed.append(f)
                continue
            reason = _waiver_for(f, waivers)
            if reason is not None:
                f.waive_reason = reason
                result.waived.append(f)
                continue
            result.findings.append(f)
    result.findings.sort(key=lambda f: (f.key, f.rule,
                                        f.line or 0))
    return result


def load_fixture(path):
    """Import a fixture module by file path and return
    (captures, waivers): the module's ``build()`` output and its
    optional ``WAIVERS`` attribute (default: no waivers — fixtures are
    judged bare unless they opt in)."""
    import importlib.util
    import os
    name = "shardlint_fixture_" + \
        os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load fixture {path!r}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return list(mod.build()), tuple(getattr(mod, "WAIVERS", ()))
