#!/usr/bin/env python
"""Round-5 on-chip measurement sweep.

One process, one backend init, all the round-5 perf experiments in
dependency order (cheapest signal first):

  1. ResNet train b128 bf16 — did the one-pass BN stat + scale/bias
     epilogue recomposition move the 15.7%-MFU row? (VERDICT item 2)
  2. Transformer remat-policy sweep at the flagship shape — is any
     selective-save policy >=5% tok/s over full remat? (item 3)
  3. fp32 fast-matmul mode — does MXTPU_FP32_MATMUL=fast lift the
     b32 fp32 train headline toward >=1,800 img/s? (item 4)

Prints one line per measurement; paste the table into
docs/perf_notes.md. The full BENCH_r05 capture stays bench.py's job.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def _sync(x):
    import bench
    bench._sync(x)


def resnet_train(batch, dtype, steps):
    import bench
    return bench.bench_train(batch, dtype, steps)


def transformer_policy(policy, steps=20):
    import jax
    import jax.numpy as jnp
    import bench
    from incubator_mxnet_tpu.models.transformer import (TransformerConfig,
                                                        TransformerLM)
    from incubator_mxnet_tpu.parallel import make_mesh

    sys.setrecursionlimit(20000)
    B, T, L, D = 32, 2048, 12, 1024
    cfg = TransformerConfig(vocab_size=32000, d_model=D, n_heads=16,
                            n_layers=L, d_ff=4 * D, max_len=T,
                            dtype="bfloat16", remat=True,
                            remat_policy=policy)
    model = TransformerLM(cfg)
    mesh = make_mesh({"dp": 1})
    step, shard_params, init_opt = model.make_train_step(
        mesh, lr=1e-3, use_sp=False, n_steps=steps)
    params = shard_params(model.init_params(jax.random.PRNGKey(0)))
    opt = init_opt(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T))
                         .astype(np.int32))
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, 1))
    params, opt, loss = step(params, opt, tokens, targets, 0)
    _sync(loss)
    params, opt, loss = step(params, opt, tokens, targets, steps)
    _sync(loss)   # second warmup at the REAL n (first-dispatch artifact)

    def run():
        nonlocal params, opt
        params, opt, loss = step(params, opt, tokens, targets, steps)
        _sync(loss)
    dt = bench._time_best(run)
    return B * T * steps / dt


def main():
    import bench
    plat = bench._wait_for_backend()
    print(f"[sweep] backend: {plat}", flush=True)
    if plat != "tpu":
        print("[sweep] WARNING: not on TPU — numbers are meaningless")

    # 1. BN one-pass effect on the ResNet train rows
    for batch, dtype, steps in ((128, "bfloat16", 240), (32, "bfloat16", 240)):
        ips = resnet_train(batch, dtype, steps)
        print(f"[sweep] resnet train b{batch} {dtype}: {ips:9.1f} img/s "
              f"(r4 b128 ref 2520, b32 ref 2432)", flush=True)

    # 2. remat-policy sweep (flagship shape)
    for policy in (None, "save_mlp", "save_attn", "save_attn_mlp", "dots"):
        try:
            tok = transformer_policy(policy)
            print(f"[sweep] transformer remat_policy={policy!r}: "
                  f"{tok:9.0f} tok/s (r4 ref ~60.3k)", flush=True)
        except Exception as e:
            print(f"[sweep] transformer remat_policy={policy!r}: "
                  f"FAILED {type(e).__name__}: {str(e)[:200]}", flush=True)

    # 3. fp32 fast-mode headline
    from incubator_mxnet_tpu import runtime
    for mode, steps in (("strict", 60), ("fast", 60)):
        runtime.set_fp32_matmul_mode(mode)
        try:
            ips = resnet_train(32, "float32", steps)
            print(f"[sweep] resnet train b32 fp32 [{mode}]: {ips:9.1f} img/s "
                  f"(r4 strict ref 597; target fast >=1800)", flush=True)
        finally:
            runtime.set_fp32_matmul_mode("strict")


if __name__ == "__main__":
    main()
