#!/usr/bin/env python
"""Per-operator throughput harness.

Reference: benchmark/opperf/ (opperf.py + rules/default_params.py) — runs
every registered op with standard input shapes and reports per-op
forward/backward latency. TPU-native: each op is timed through its
jit-cached eager path (the same dispatch users hit), batched k runs per
measurement with a device sync only at the ends, so the number reflects
op kernel time, not host round-trips.

usage:
  python benchmark/opperf.py                   # curated core set
  python benchmark/opperf.py --ops dot,Convolution --shape-size large
  python benchmark/opperf.py --json out.json
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _profiles(size):
    s = {"small": 1, "default": 4, "large": 16}[size]
    n = 64 * s
    return {
        "elemwise": [((n, n), (n, n))],
        "reduce": [((n, n),)],
        "dot": [((n, n), (n, n))],
        "conv": [((8, 32, 28, 28), (64, 32, 3, 3))],
        "fc": [((32, n), (256, n), (256,))],
        "norm": [((8, 32, 28, 28),)],
        "softmax": [((32, 1000),)],
    }


# curated op set: name -> (profile, param dict, positional arg builder)
CORE_OPS = {
    "broadcast_add": ("elemwise", {}),
    "broadcast_mul": ("elemwise", {}),
    "elemwise_add": ("elemwise", {}),
    "exp": ("reduce", {}),
    "relu": ("reduce", {}),
    "sigmoid": ("reduce", {}),
    "sum": ("reduce", {}),
    "mean": ("reduce", {}),
    "max": ("reduce", {}),
    "dot": ("dot", {}),
    "transpose": ("reduce", {}),
    "Convolution": ("conv", {"kernel": (3, 3), "num_filter": 64,
                             "no_bias": True}),
    "Pooling": ("norm", {"kernel": (2, 2), "pool_type": "max",
                         "stride": (2, 2)}),
    "FullyConnected": ("fc", {"num_hidden": 256}),
    # train_aware ops get training=True explicitly — outside
    # autograd.record() they would otherwise run their inference paths
    # (Dropout = identity) and the timing would be meaningless
    "BatchNorm": ("norm", {"training": True}),
    "LayerNorm": ("softmax", {}),
    "softmax": ("softmax", {}),
    "log_softmax": ("softmax", {}),
    "Activation": ("reduce", {"act_type": "relu"}),
    "Dropout": ("reduce", {"p": 0.5, "training": True}),
}


def _build_args(op_name, profile, shapes, nd):
    arrs = [nd.array(np.random.uniform(-1, 1, s).astype(np.float32))
            for s in shapes]
    if op_name == "BatchNorm":
        c = shapes[0][1]
        extra = [nd.array(np.random.uniform(0.5, 1.5, c).astype(np.float32)),
                 nd.array(np.zeros(c, np.float32)),
                 nd.array(np.zeros(c, np.float32)),
                 nd.array(np.ones(c, np.float32))]
        return arrs + extra
    if op_name == "LayerNorm":
        c = shapes[0][-1]
        return arrs + [nd.array(np.ones(c, np.float32)),
                       nd.array(np.zeros(c, np.float32))]
    return arrs


def _sync(out):
    leaves = out if isinstance(out, (list, tuple)) else [out]
    for o in leaves:
        d = getattr(o, "_data", o)
        if hasattr(d, "block_until_ready"):
            d.block_until_ready()


def bench_op(op_name, profile, params, size, runs, warmup, with_backward):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    nd = mx.nd
    shapes = _profiles(size)[profile][0]
    args = _build_args(op_name, profile, shapes, nd)
    op = getattr(nd, op_name)

    for _ in range(warmup):
        _sync(op(*args, **params))
    t0 = time.perf_counter()
    for _ in range(runs):
        out = op(*args, **params)
    _sync(out)
    fwd_ms = (time.perf_counter() - t0) / runs * 1e3

    bwd_ms = None
    if with_backward:
        try:
            for a in args:
                a.attach_grad()
            with autograd.record():
                out = op(*args, **params)
                head = out[0] if isinstance(out, (list, tuple)) else out
            head.backward()           # warms the cached vjp executable
            t0 = time.perf_counter()
            for _ in range(runs):
                with autograd.record():
                    out = op(*args, **params)
                    head = out[0] if isinstance(out, (list, tuple)) else out
                head.backward()
            _sync(args[0].grad)
            bwd_ms = (time.perf_counter() - t0) / runs * 1e3
        except Exception:
            bwd_ms = None
    return {"op": op_name, "shapes": [list(s) for s in shapes],
            "fwd_ms": round(fwd_ms, 4),
            "fwd_bwd_ms": round(bwd_ms, 4) if bwd_ms else None}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset (default: curated core set)")
    ap.add_argument("--shape-size", default="default",
                    choices=["small", "default", "large"])
    ap.add_argument("--runs", type=int, default=25)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--no-backward", action="store_true")
    ap.add_argument("--json", default=None, help="write results to file")
    args = ap.parse_args()

    names = args.ops.split(",") if args.ops else list(CORE_OPS)
    results = []
    for name in names:
        if name not in CORE_OPS:
            print(f"[opperf] skip {name}: no profile", file=sys.stderr)
            continue
        profile, params = CORE_OPS[name]
        try:
            r = bench_op(name, profile, params, args.shape_size, args.runs,
                         args.warmup, not args.no_backward)
        except Exception as e:
            print(f"[opperf] {name} FAILED: {e!r}", file=sys.stderr)
            continue
        results.append(r)
        bwd = f"  fwd+bwd {r['fwd_bwd_ms']:9.3f} ms" if r["fwd_bwd_ms"] \
            else ""
        print(f"[opperf] {name:20s} fwd {r['fwd_ms']:9.3f} ms{bwd}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    return 0 if results else 1


if __name__ == "__main__":
    sys.exit(main())
