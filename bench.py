#!/usr/bin/env python
"""Benchmark harness.

Measures hybridized/compiled ResNet-50 ImageNet-shape throughput on the
available chip and compares against the reference's published numbers
(BASELINE.md, from docs/faq/perf.md: train fp32 b32 = 298.51 img/s,
b128 = 363.69, inference fp32 b32 = 1,076.81 on 1x V100; scripts
example/image-classification/benchmark_score.py + train_imagenet.py).

stdout: ONE JSON line for the headline metric
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}
stderr: the full table (all configs + MFU).
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


# fwd-pass GFLOPs per 224x224 image (standard ResNet-50 conv+fc count);
# training approximated at 3x forward (fwd + 2x bwd)
RESNET50_FWD_GFLOP = 4.09
BASELINES = {  # from BASELINE.md (1x V100)
    ("train", 32, "float32"): 298.51,
    ("train", 128, "float32"): 363.69,
    ("inference", 32, "float32"): 1076.81,
    ("inference", 32, "bfloat16"): 2085.51,   # fp16 row
}
# dense peak TFLOP/s per chip for MFU (bf16; fp32 counted at the same MXU
# peak since TPUs compute fp32 matmuls via bf16 passes)
PEAK_TFLOPS = {
    "TPU v4": 275, "TPU v5 lite": 197, "TPU v5e": 197, "TPU v5": 459,
    "TPU v5p": 459, "TPU v6e": 918, "TPU v6": 918, "TPU v7": 4614,
}


def _wait_for_backend(max_wait=None):
    """Poll until the JAX backend is actually reachable, with a bounded
    retry/backoff loop (default 10 min, MXTPU_BENCH_INIT_TIMEOUT to
    override). The TPU tunnel can be transiently Unavailable — and a bad
    tunnel makes jax.devices() HANG rather than raise, so each probe runs
    in a subprocess with its own timeout; the parent only initializes its
    backend after a probe has succeeded. When the configured accelerator
    never comes up within the deadline, retries the probe pinned to
    JAX_PLATFORMS=cpu and continues there — a CPU round with real
    numbers beats an empty BENCH json (rounds 4-5 published nulls
    because a dead tunnel zeroed the whole run). Returns the platform
    string, or None only when even the CPU backend is unusable (caller
    emits the null JSON line rather than dying in jax.devices()). The
    reference's analog is its benchmark loop's resilience to warm-up
    noise (example/image-classification/benchmark_score.py)."""
    import os
    import subprocess
    if max_wait is None:
        max_wait = float(os.environ.get("MXTPU_BENCH_INIT_TIMEOUT", "600"))
    probe = [sys.executable, "-c",
             "import os, jax;"
             " p = os.environ.get('JAX_PLATFORMS');"
             " p and jax.config.update('jax_platforms', p);"
             " print('PLATFORM=' + jax.devices()[0].platform)"]
    deadline = time.time() + max_wait
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.time()
        if remaining <= 0:
            if os.environ.get("JAX_PLATFORMS") != "cpu":
                try:
                    r = subprocess.run(
                        probe, capture_output=True, text=True, timeout=120,
                        env=dict(os.environ, JAX_PLATFORMS="cpu"))
                    for line in r.stdout.splitlines():
                        if line.startswith("PLATFORM="):
                            print("[bench] configured backend never came "
                                  "up; FALLING BACK to JAX_PLATFORMS=cpu "
                                  "so this round still publishes numbers",
                                  file=sys.stderr)
                            os.environ["JAX_PLATFORMS"] = "cpu"
                            return line.split("=", 1)[1]
                except (subprocess.TimeoutExpired, OSError):
                    pass
            return None
        try:
            r = subprocess.run(
                probe, capture_output=True, text=True,
                timeout=max(30.0, min(120.0, remaining)))
            for line in r.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    return line.split("=", 1)[1]
            err = (r.stderr or "").strip().splitlines()
            print(f"[bench] backend probe {attempt} failed (rc={r.returncode})"
                  + (f": {err[-1][:200]}" if err else ""), file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"[bench] backend probe {attempt} timed out (backend hung)",
                  file=sys.stderr)
        time.sleep(min(20.0, 2.0 * attempt, max(0.0, deadline - time.time())))


def _sync(x):
    """Wait for x AND force a one-element host readback: through tunneled
    backends block_until_ready can resolve before device completion, which
    would time dispatch instead of compute. NDArray results are unwrapped
    to their jax buffer first — an unregistered wrapper leaf would
    otherwise make this a silent no-op and time nothing."""
    import jax
    leaves = [getattr(a, "_data", a) for a in jax.tree_util.tree_leaves(x)]
    leaves = [a for a in leaves if hasattr(a, "block_until_ready")]
    for a in leaves:
        a.block_until_ready()
    if leaves:
        last = leaves[-1]
        raw = getattr(last, "_data", last)
        np.asarray(raw.reshape(-1)[:1])


def _device_peak():
    import jax
    kind = jax.devices()[0].device_kind
    for k, v in sorted(PEAK_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.lower().startswith(k.lower()):
            return kind, v * 1e12
    return kind, None


def _aot_cost(key, jitted, *args):
    """Compiler cost summary {flops, bytes_accessed, ...} for a jitted
    callable at these args, recorded into the profiler cost table. Uses
    the AOT Lowered (XLA's HLO cost analysis — no second backend compile);
    returns {} when the backend can't report."""
    from incubator_mxnet_tpu import profiler
    try:
        return profiler.cost_from_executable(key, jitted.lower(*args))
    except Exception as e:  # noqa: BLE001 — cost is telemetry, not a result
        print(f"[bench] {key}: compiler cost unavailable ({e!r})",
              file=sys.stderr)
        return {}


def _check_flops_agreement(name, analytic, compiler, strict):
    """Cross-check the compiler's reported FLOPs against the analytic
    formula; >10% disagreement means one of the two models is wrong.
    Strict (raises) on TPU where cost_analysis is authoritative; on CPU
    it warns — XLA:CPU analyzes a differently-optimized module. Returns
    the relative error (None when either side is missing)."""
    if not analytic or not compiler:
        return None
    rel = abs(compiler - analytic) / analytic
    if rel > 0.10:
        msg = (f"[bench] {name}: compiler FLOPs {compiler:.4g} vs analytic "
               f"{analytic:.4g} disagree by {rel * 100:.1f}% (>10%)")
        if strict:
            raise AssertionError(msg)
        print(msg + " -- tolerated off-TPU", file=sys.stderr)
    return rel


def _phase_probe(run_one_step):
    """Run one step with step-time attribution forced on and return its
    {phase: ms} breakdown (rounded). The caller must have warmed up
    already so compile time doesn't masquerade as compute."""
    from incubator_mxnet_tpu import profiler
    prev = profiler.attribution_enable(True)
    try:
        run_one_step()
        profiler.phase_step_end()
        phases = profiler.last_step_phases()
    finally:
        profiler.attribution_enable(prev)
    return {k: round(v, 3) for k, v in phases.items()}


def bench_train(batch, dtype, steps, image_size=224):
    """Fully-compiled train loop: `steps` optimizer steps run inside ONE
    XLA program (TrainStep.run_steps scans the fused fwd+bwd+SGD step with
    params carried on device). One dispatch per measurement, so a tunneled
    device's per-call RPC latency (~100s of ms here) doesn't pollute the
    steady-state number — the reference's analog is engine op-bulking
    (graph_executor.cc:1288) keeping Python off the hot path."""
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel import TrainStep

    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())

    def loss_fn(out, label):
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, label[:, None], axis=1))

    x0 = mx.nd.array(np.random.randn(batch, 3, image_size, image_size)
                     .astype(np.float32))
    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.01,
                                       "momentum": 0.9},
                     example_inputs=[x0],
                     dtype=dtype if dtype != "float32" else None)

    # stage the synthetic batch on-device once: we measure compute, not the
    # host link (the input pipeline overlaps transfers in real training)
    x = jnp.asarray(np.random.randn(batch, 3, image_size, image_size)
                    .astype(np.float32))
    if dtype != "float32":
        x = x.astype(dtype)
    y = jnp.asarray(np.random.randint(0, 1000, batch).astype(np.int32))
    _sync(x), _sync(y)
    _sync(step.run_steps(steps, x, y))    # compile + warmup
    dt = _time_best(lambda: _sync(step.run_steps(steps, x, y)))

    # observability row extras: per-phase breakdown of one attributed
    # single step through TrainStep.__call__ (h2d/compute spans with a
    # device sync), plus the compiler's own cost model for that step —
    # the cached_jit trainstep executable records cost_analysis() into
    # the profiler compile table as a side effect of compiling
    extras = {}
    try:
        from incubator_mxnet_tpu import profiler
        prev = profiler.attribution_enable(True)   # cost hook is gated
        try:
            _sync(step(x, y))             # compile the 1-step executable
            extras["phase_ms"] = _phase_probe(lambda: step(x, y))
            cost = profiler.cost_stats()
        finally:
            profiler.attribution_enable(prev)
        for key, row in cost.items():
            if key.startswith("trainstep:") and row.get("flops"):
                extras["compiler_flops_per_step"] = row["flops"]
                if row.get("bytes_accessed"):
                    extras["compiler_bytes_per_step"] = row["bytes_accessed"]
    except Exception as e:  # noqa: BLE001 — extras must not fail the row
        print(f"[bench] train b{batch} {dtype}: attribution probe failed "
              f"({e!r})", file=sys.stderr)
    return batch * steps / dt, extras


def _time_best(run, n=2):
    """Best (min) of n timed dispatches of `run` (which must block until
    results are ready). A one-off tunnel/compile-helper stall during a
    single window was observed to misreport 59.7k tok/s as 5.3k; min-of-n
    is the standard defense."""
    dt = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        run()
        dt = min(dt, time.perf_counter() - t0)
    return dt


def bench_inference(batch, dtype, steps, image_size=224):
    """Hybridized forward (benchmark_score.py analog): `steps` forward
    passes scanned inside one XLA program. The carry feeds back into the
    input (a negligible elementwise add) so XLA cannot hoist the network
    out of the loop as loop-invariant."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel.functional import functionalize
    from incubator_mxnet_tpu.parallel.train import default_compiler_options

    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    if dtype != "float32":
        net.cast(dtype)
    x0 = mx.nd.array(np.random.randn(batch, 3, image_size, image_size)
                     .astype(np.float32)).astype(dtype)
    params, apply_fn = functionalize(net, [x0], training=False)
    rng = jax.random.PRNGKey(0)
    xa = x0._data

    def loop(p, r, xx):
        def body(c, _):
            out = apply_fn(p, r, xx + c.astype(xx.dtype))[0][0]
            return out.astype(jnp.float32).mean() * 1e-12, None
        s, _ = lax.scan(body, jnp.float32(0), None, length=steps)
        return s

    fwd = jax.jit(loop, compiler_options=default_compiler_options())
    _sync(fwd(params, rng, xa))
    dt = _time_best(lambda: _sync(fwd(params, rng, xa)))

    extras = {}
    try:
        from incubator_mxnet_tpu import profiler

        def one():
            with profiler.span("compute"):
                _sync(fwd(params, rng, xa))
        extras["phase_ms"] = {
            k: round(v / steps, 3)
            for k, v in _phase_probe(one).items()}    # per forward pass
        cost = _aot_cost(f"bench:inference[b{batch},{dtype}]", fwd,
                         params, rng, xa)
        if cost.get("flops"):
            # the lowered program scans `steps` forwards: report per step
            extras["compiler_flops_per_step"] = cost["flops"] / steps
    except Exception as e:  # noqa: BLE001
        print(f"[bench] inference b{batch} {dtype}: attribution probe "
              f"failed ({e!r})", file=sys.stderr)
    return batch * steps / dt, extras


def bench_transformer(steps=20):
    """Transformer-LM flagship train step (models/transformer.py): the
    matmul-bound workload where the MXU shows its real utilization —
    ResNet-50's conv backward is HBM-bound at ~16% MFU by roofline
    (docs/perf_notes.md), a transformer step is not. GPT-style 12x1024
    model, seq 2048, batch 32, Adam, remat, bf16; one scanned
    multi-step program. Returns (tokens_per_sec, mfu)."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.models.transformer import (TransformerConfig,
                                                        TransformerLM)
    from incubator_mxnet_tpu.parallel import make_mesh

    import sys as _sys
    _sys.setrecursionlimit(20000)   # 30-step scan of a 12-layer remat graph
    B, T, L, D = 32, 2048, 12, 1024
    cfg = TransformerConfig(vocab_size=32000, d_model=D, n_heads=16,
                            n_layers=L, d_ff=4 * D, max_len=T,
                            dtype="bfloat16", remat=True)
    model = TransformerLM(cfg)
    mesh = make_mesh({"dp": 1})
    step, shard_params, init_opt = model.make_train_step(
        mesh, lr=1e-3, use_sp=False, n_steps=steps)
    params = shard_params(model.init_params(jax.random.PRNGKey(0)))
    n_matmul = sum(v.size for k, v in params.items()
                   if k.endswith(("wq", "wk", "wv", "wo", "w_in", "w_out")))
    n_embed = params["embed"].size
    opt = init_opt(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T))
                         .astype(np.int32))
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, 1))

    # TWO warmups at the REAL step count: the first dispatch of a given
    # n-step program carries ~1s of one-time cost even after another
    # program compiled (measured r4 — this artifact is what made flash
    # attention look slower than dense in r3)
    params, opt, loss = step(params, opt, tokens, targets, 0)  # compile
    _sync(loss)
    params, opt, loss = step(params, opt, tokens, targets, steps)
    _sync(loss)
    def run():
        nonlocal params, opt
        params, opt, loss = step(params, opt, tokens, targets, steps)
        _sync(loss)
    dt = _time_best(run)
    tok_s = B * T * steps / dt
    # 6*N per token over matmul+embedding-output params, plus the
    # attention quadratic: fwd 4*B*T^2*D per layer, x3 for train
    flops_step = 6.0 * (n_matmul + n_embed) * B * T + 12.0 * L * B * T * T * D
    _, peak = _device_peak()
    # MFU from the compiler's cost model when the step function exposes
    # AOT lowering; the analytic formula stays as the strict cross-check
    # (bench_transformer only runs on TPU, where cost_analysis is
    # authoritative)
    compiler_step = None
    if hasattr(step, "lower"):
        cost = _aot_cost("bench:transformer", step,
                         params, opt, tokens, targets, 0)
        if cost.get("flops"):
            compiler_step = cost["flops"] / steps
            _check_flops_agreement("transformer train", flops_step,
                                   compiler_step, strict=True)
    used = compiler_step if compiler_step else flops_step
    mfu = used * steps / dt / peak if peak else None
    return tok_s, mfu


def bench_transformer_longctx(steps=8):
    """Long-context training row: T=8192 with the Pallas flash-attention
    forward+backward kernels (O(block*T) memory) — the XLA attention path
    cannot compile this shape on one chip (HBM OOM on materialized
    scores). Returns (tokens_per_sec, seq_len)."""
    import sys as _sys
    _sys.setrecursionlimit(40000)
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.models.transformer import (TransformerConfig,
                                                        TransformerLM)
    from incubator_mxnet_tpu.parallel import make_mesh

    B, T, L, D = 4, 8192, 12, 1024
    cfg = TransformerConfig(vocab_size=32000, d_model=D, n_heads=16,
                            n_layers=L, d_ff=4 * D, max_len=T,
                            dtype="bfloat16", remat=True,
                            flash_attention=True)
    model = TransformerLM(cfg)
    mesh = make_mesh({"dp": 1})
    step, shard_params, init_opt = model.make_train_step(
        mesh, lr=1e-3, use_sp=False, n_steps=steps)
    params = shard_params(model.init_params(jax.random.PRNGKey(0)))
    opt = init_opt(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T))
                         .astype(np.int32))
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, 1))
    params, opt, loss = step(params, opt, tokens, targets, 0)
    _sync(loss)
    params, opt, loss = step(params, opt, tokens, targets, steps)
    _sync(loss)   # second warmup: first dispatch of the n-step program
    def run():
        nonlocal params, opt
        params, opt, loss = step(params, opt, tokens, targets, steps)
        _sync(loss)
    dt = _time_best(run)
    return B * T * steps / dt, T


def bench_int8_inference(batch, steps, image_size=224):
    """INT8 inference through the quantization driver: zoo resnet50 ->
    export -> BatchNorm fold -> calibrated int8 graph (quantized conv/fc
    on the MXU with int32 accumulation, int8 chains through relu/pool),
    evaluated in one scanned XLA program. v5e's int8 MXU peak is 2x bf16,
    so MFU here is computed against 394 TOPS."""
    import tempfile

    import jax
    import jax.numpy as jnp
    from jax import lax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.contrib.quantization import (fold_batchnorm,
                                                          quantize_model)
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel.train import default_compiler_options
    import incubator_mxnet_tpu.io as mio

    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((1, 3, image_size, image_size), np.float32)))
    with tempfile.TemporaryDirectory() as d:
        prefix = d + "/rn50"
        net.export(prefix)
        sym, args, aux = mx.model.load_checkpoint(prefix, 0)
    sym, args, aux = fold_batchnorm(sym, args, aux)
    rng_np = np.random.RandomState(0)
    calib = mio.NDArrayIter(
        data=rng_np.rand(8, 3, image_size, image_size).astype(np.float32),
        batch_size=8)
    qsym, qargs, qaux = quantize_model(
        sym, args, aux, data_names=("data",), calib_mode="naive",
        calib_data=calib, num_calib_examples=8, quantized_dtype="int8")

    names = sorted(qargs) + sorted(qaux)
    pvals = [jnp.asarray((qargs | qaux)[n]._data
                         if hasattr((qargs | qaux)[n], "_data")
                         else (qargs | qaux)[n].asnumpy()) for n in names]

    def one(pv, x):
        feed = {n: NDArray(v) for n, v in zip(names, pv)}
        feed["data"] = NDArray(x)
        out = qsym.eval_dict(feed)
        out = out[0] if isinstance(out, list) else out
        return out._data

    x0 = jnp.asarray(rng_np.rand(batch, 3, image_size, image_size)
                     .astype(np.float32))

    def loop(pv, xx):
        def body(c, _):
            o = one(pv, xx + c.astype(xx.dtype))
            return o.astype(jnp.float32).mean() * 1e-12, None
        s, _ = lax.scan(body, jnp.float32(0), None, length=steps)
        return s

    fwd = jax.jit(loop, compiler_options=default_compiler_options())
    _sync(fwd(pvals, x0))
    dt = _time_best(lambda: _sync(fwd(pvals, x0)))
    return batch * steps / dt


def bench_lstm_ptb(steps, batch=32, bptt=35):
    """LSTM word-LM train step (BASELINE config 3: example/rnn/word_lm/
    train.py, the cuDNN-RNN path there; ops/rnn_ops.py scan kernels here).
    Reference small config: vocab 10k, 2x200 LSTM, bptt 35. The fused
    fwd+bwd+SGD step runs `steps` times inside one XLA program via
    TrainStep.run_steps, same discipline as bench_train. Returns tok/s."""
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn, rnn
    from incubator_mxnet_tpu.parallel import TrainStep

    vocab, emsize, nhid, nlayers = 10000, 200, 200, 2

    class WordLM(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.embed = nn.Embedding(vocab, emsize)
            self.lstm = rnn.LSTM(nhid, num_layers=nlayers, layout="NTC")
            self.decoder = nn.Dense(vocab, flatten=False)

        def forward(self, x):
            return self.decoder(self.lstm(self.embed(x)))

    net = WordLM()
    net.initialize(mx.init.Xavier())

    def loss_fn(out, label):
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, label[..., None],
                                             axis=-1))

    rng = np.random.RandomState(0)
    x0 = mx.nd.array(rng.randint(0, vocab, (batch, bptt)).astype(np.int32))
    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 1.0},
                     example_inputs=[x0])
    x = jnp.asarray(rng.randint(0, vocab, (batch, bptt)).astype(np.int32))
    y = jnp.asarray(np.roll(np.asarray(x), -1, 1))
    _sync(step.run_steps(steps, x, y))    # compile + warmup
    dt = _time_best(lambda: _sync(step.run_steps(steps, x, y)))
    return batch * bptt * steps / dt


def bench_ssd_detection(steps, batch=8, image_size=128):
    """SSD detection train step (BASELINE config 4: example/ssd/train.py,
    SSD-VGG16 there, the ToySSD of our example here). Exercises the
    multibox op stack end to end — MultiBoxPrior anchors, MultiBoxTarget
    assignment with hard-negative mining, joint cls+box loss — through
    the eager autograd path the example trains with (per-op compiled
    executables; the target-assignment op has data-dependent shapes that
    keep it off the scanned-program path). Returns img/s."""
    import importlib.util
    import os as _os
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    spec = importlib.util.spec_from_file_location(
        "ssd_train", _os.path.join(_os.path.dirname(
            _os.path.abspath(__file__)), "example", "ssd", "train.py"))
    ssd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ssd)

    rng = np.random.RandomState(0)
    model = ssd.ToySSD(mx, gluon, num_classes=1)
    trainer = gluon.Trainer(model.params(gluon), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss(rho=1.0)
    xb, lb = ssd.make_batch(rng, batch, image_size)
    x, label = nd.array(xb), nd.array(lb)

    def one_step():
        with autograd.record():
            anchors, cls_pred, box_pred = model.forward(nd, x)
            box_t, box_m, cls_t = nd.contrib.MultiBoxTarget(
                anchors, label, cls_pred.transpose((0, 2, 1)),
                overlap_threshold=0.5, negative_mining_ratio=3.0,
                minimum_negative_samples=0,
                variances=(0.1, 0.1, 0.2, 0.2))
            loss = (cls_loss(cls_pred, cls_t)
                    + box_loss(box_pred * box_m, box_t * box_m))
        loss.backward()
        trainer.step(batch)
        return loss

    _sync(one_step())                     # compile + warmup

    def run():
        for _ in range(steps):
            loss = one_step()
        _sync(loss)

    dt = _time_best(run)
    return batch * steps / dt


def bench_fused_step(steps, n_params=64, dim=64):
    """Aggregated eager train step: the dispatch-bound regime the fused
    optimizer path targets (many small params — embeddings, norms, biases).
    Times the same eager loop with aggregation on (bucketed fused updates +
    flat-packed gradient collectives, gluon/trainer.py) and off
    (engine.bulk(1): one jit dispatch + one collective per parameter).
    Returns (fused_steps_per_s, unfused_steps_per_s, fused_dispatches,
    unfused_dispatches) — dispatch counts per step from the Trainer's
    observability counters."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, gluon, nd

    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(dim, dim).astype(np.float32))

    def make_trainer():
        params = gluon.ParameterDict()
        for j in range(n_params):
            p = params.get(f"w{j:03d}", shape=(dim, dim), init="zeros")
            p.initialize()
            p.set_data(nd.array(rng.randn(dim, dim).astype(np.float32)))
        tr = gluon.Trainer(params, "sgd",
                           {"learning_rate": 0.01, "momentum": 0.9},
                           kvstore="tpu")
        return tr, [params[k] for k in sorted(params.keys())]

    def loop(tr, plist, n):
        for _ in range(n):
            with autograd.record():
                loss = plist[0].data().reshape(-1)[0] * 0
                for p in plist:
                    loss = loss + (p.data() * x).sum()
            loss.backward()
            tr.step(1)
        _sync(plist[-1].data())

    tr_f, pl_f = make_trainer()
    loop(tr_f, pl_f, 1)                   # compile + warmup
    dt_f = _time_best(lambda: loop(tr_f, pl_f, steps))
    disp_f = tr_f._last_step_dispatches

    tr_u, pl_u = make_trainer()
    with engine.bulk(1):
        loop(tr_u, pl_u, 1)
        dt_u = _time_best(lambda: loop(tr_u, pl_u, steps))
        disp_u = tr_u._last_step_dispatches
    return steps / dt_f, steps / dt_u, disp_f, disp_u


def bench_input_pipeline(steps, batch=32, image_size=64):
    """Input-pipeline overlap row: iterate a DataLoader and run a jitted
    reduction per batch, synchronous (pin_memory=False — batchify and the
    H2D copy serialize with the consumer) vs the double-buffered device
    prefetch (pin_memory=True — io/prefetch.py stages batch N+1's async
    host->HBM copy under batch N's compute, iter_prefetcher.h's double
    buffer extended past host RAM). Returns (sync_img_s, prefetch_img_s)."""
    import jax
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    n = batch * max(steps, 4)
    rs = np.random.RandomState(0)
    X = rs.rand(n, 3, image_size, image_size).astype(np.float32)
    Y = rs.randint(0, 10, n).astype(np.float32)
    ds = ArrayDataset(X, Y)

    @jax.jit
    def compute(x):
        v = x.reshape(x.shape[0], -1)
        return (v @ v.T).sum()

    def consume(pin):
        out = None
        for xb, _ in DataLoader(ds, batch_size=batch, shuffle=False,
                                pin_memory=pin):
            out = compute(xb._data)
        _sync(out)

    consume(False)                        # compile + warmup
    dt_sync = _time_best(lambda: consume(False))
    dt_pin = _time_best(lambda: consume(True))
    return n / dt_sync, n / dt_pin


def bench_fused_block(steps, batch=16, image_size=64):
    """Fused residual-block row: the same ResNet-18 train loop with the
    gluon fused path on (MXTPU_FUSED_BLOCK=1 — blocks lower through the
    autotuned FusedConvBNReLU / FusedBNAddReLU ops) vs off (the
    layer-by-layer Conv/BatchNorm/relu oracle). Off-TPU the tuner's
    candidate sets are empty and both sides run the identical XLA
    composition, so this row only separates on a real accelerator.
    Returns (fused_img_s, unfused_img_s)."""
    import os
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel import TrainStep

    def loss_fn(out, label):
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, label[:, None], axis=1))

    rs = np.random.RandomState(0)
    xh = rs.randn(batch, 3, image_size, image_size).astype(np.float32)
    x = jnp.asarray(xh)
    y = jnp.asarray(rs.randint(0, 100, batch).astype(np.int32))
    _sync(x), _sync(y)

    def run_one(fused):
        prev = os.environ.get("MXTPU_FUSED_BLOCK")
        os.environ["MXTPU_FUSED_BLOCK"] = "1" if fused else "0"
        try:
            net = vision.resnet18_v1(classes=100)
            net.initialize(mx.init.Xavier())
            step = TrainStep(net, loss_fn, optimizer="sgd",
                             optimizer_params={"learning_rate": 0.01,
                                               "momentum": 0.9},
                             example_inputs=[mx.nd.array(xh)])
            _sync(step.run_steps(steps, x, y))      # compile + warmup
            dt = _time_best(lambda: _sync(step.run_steps(steps, x, y)))
        finally:
            if prev is None:
                os.environ.pop("MXTPU_FUSED_BLOCK", None)
            else:
                os.environ["MXTPU_FUSED_BLOCK"] = prev
        return batch * steps / dt

    return run_one(True), run_one(False)


def bench_checkpoint(steps, batch=32, dim=512, every=100):
    """Checkpoint-overhead row (robustness cost tracking): the same
    compiled MLP train loop uncheckpointed, with a SYNCHRONOUS
    fault.CheckpointManager.save every `every` steps (fsync'd write on
    the step path — what PR 8 replaces), and with
    fault.AsyncCheckpointManager.save_async (write-behind: the step only
    pays the device->host snapshot; the writer thread owns the disk).
    Fixed model size: a 4x Dense(dim) MLP. Returns (base_sps, sync_sps,
    async_sps) steps/s; overhead %% derived by the caller."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import fault
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.parallel import TrainStep

    net = nn.HybridSequential()
    for _ in range(3):
        net.add(nn.Dense(dim, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx.init.Xavier())

    def loss_fn(out, label):
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, label[:, None], axis=1))

    rs = np.random.RandomState(0)
    xh = rs.randn(batch, dim).astype(np.float32)
    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.01,
                                       "momentum": 0.9},
                     example_inputs=[mx.nd.array(xh)])
    x = jnp.asarray(xh)
    y = jnp.asarray(rs.randint(0, 10, batch).astype(np.int32))
    _sync(step(x, y))                     # compile + warmup

    def loop(manager):
        for i in range(steps):
            # fetch the loss every step (the usual logging pattern) so all
            # three variants pay the same dispatch barrier and the delta is
            # checkpoint cost, not lost pipeline overlap
            _sync(step(x, y))
            if manager is not None and (i + 1) % every == 0:
                step.save_checkpoint(manager, data_state={"batch": i + 1})

    dt_base = _time_best(lambda: loop(None))
    with tempfile.TemporaryDirectory() as d:
        sync_mgr = fault.CheckpointManager(d, prefix="s", max_keep=2)
        dt_sync = _time_best(lambda: loop(sync_mgr))
        async_mgr = fault.AsyncCheckpointManager(d, prefix="a", max_keep=2)
        try:
            dt_async = _time_best(lambda: loop(async_mgr))
            async_mgr.flush(timeout=60)   # writes land AFTER the timed
            #                               window — that is the point
        finally:
            async_mgr.close()
    return steps / dt_base, steps / dt_sync, steps / dt_async


_COLD_START_SCRIPT = """
import json, os, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, profiler
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.serve import Predictor

prefix = os.environ["MXTPU_BENCH_ARTIFACT"]
if sys.argv[1] == "export":
    net = nn.HybridSequential()
    for _ in range(6):
        net.add(nn.Dense(512, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize()
    net(nd.array(np.zeros((1, 256), np.float32)))
    net.export(prefix)
    print(json.dumps({{"ok": True}}))
else:
    t0 = time.perf_counter()
    pred = Predictor.from_artifact(prefix,
                                   bucket_sizes=(1, 2, 4, 8, 16, 32),
                                   input_shapes={{"data": (1, 256)}},
                                   prewarm=True)
    out = pred.predict({{"data": np.zeros((4, 256), np.float32)}})
    np.asarray(out[0])
    ttfp = (time.perf_counter() - t0) * 1e3
    wall = sum(v["compile_ms"] for v in profiler.compile_stats().values())
    from incubator_mxnet_tpu import compile_cache as cc
    s = cc.stats()
    print(json.dumps({{"ttfp_ms": ttfp, "compile_wall_ms": wall,
                       "misses": s["misses"], "disk_hits": s["disk_hits"]}}))
"""


def bench_serve_cold_start():
    """Fleet cold-start row: time-to-first-prediction of a *fresh
    process* booting a Predictor (construct + prewarm every ladder
    bucket + one real predict) against a cold vs warm
    MXNET_EXEC_CACHE_DIR. The warm boot deserializes AOT executables
    from the shared dir instead of re-tracing (compile_cache.py) — the
    ">=3x faster TTFP" acceptance criterion of the cold-start
    milestone. Runs pinned to CPU: the row measures the cache, not the
    chip, and must produce numbers even when the TPU tunnel is down.
    Returns (cold, warm) dicts of {ttfp_ms, compile_wall_ms, misses,
    disk_hits} reported from inside the booting process (interpreter +
    jax import excluded: those are paid identically either way)."""
    import os
    import subprocess
    import tempfile
    d = tempfile.mkdtemp(prefix="mxec_bench_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_EXEC_CACHE_DIR=os.path.join(d, "cache"),
               MXTPU_BENCH_ARTIFACT=os.path.join(d, "model"))
    script = _COLD_START_SCRIPT.format(
        repo=os.path.dirname(os.path.abspath(__file__)))

    def run(mode):
        r = subprocess.run([sys.executable, "-c", script, mode], env=env,
                           capture_output=True, text=True, timeout=600)
        if r.returncode != 0:
            raise RuntimeError(f"cold-start {mode} subprocess failed: "
                               f"{(r.stderr or '').strip()[-500:]}")
        return json.loads(r.stdout.strip().splitlines()[-1])

    run("export")
    cold = run("boot")
    warm = run("boot")
    return cold, warm


_COMPOSED_1F1B_SCRIPT = """
import json, os, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
import jax
import jax.numpy as jnp
from incubator_mxnet_tpu import profiler
from incubator_mxnet_tpu.parallel import make_mesh
from incubator_mxnet_tpu.models.composed import (ComposedConfig,
                                                 ComposedPipelineLM)

S, M = 4, 8
cfg = ComposedConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=8,
                     d_ff=64, n_experts=4, moe_every=2, capacity_factor=4.0,
                     aux_weight=0.01, max_len=64, dtype="float32")
model = ComposedPipelineLM(cfg)
mesh = make_mesh({{"dp": 2, "pp": S}})
rng = np.random.RandomState(0)
tokens = jnp.asarray(rng.randint(0, 64, (16, 16)).astype(np.int32))
targets = jnp.asarray(rng.randint(0, 64, (16, 16)).astype(np.int32))
prev = profiler.attribution_enable(True)
out = {{}}
for sched, remat, v, off in (("gpipe", "none", 1, False),
                             ("1f1b", "dots_saveable", 1, False),
                             ("interleaved", "none", 2, False),
                             ("zb1", "none", 1, False),
                             ("gpipe_offload", "none", 1, True)):
    real = sched.split("_")[0]
    step, shard_params, init_opt = model.make_train_step(
        mesh, n_microbatches=M, schedule=real, remat=remat,
        n_chunks=(v if v > 1 else None), offload=off)
    p = shard_params(model.init_params(jax.random.PRNGKey(0), S,
                                       n_chunks=v))
    opt = init_opt(p)
    for _ in range(2):   # cold compile + the one sharding respecialization
        p, opt, loss = step(p, opt, tokens, targets, 0)
    best = float("inf")
    for i in range(3):
        t0 = time.perf_counter()
        p, opt, loss = step(p, opt, tokens, targets, i + 2)
        jax.block_until_ready(loss)
        best = min(best, time.perf_counter() - t0)
    phases = profiler.last_step_phases()
    bub = phases.get("pp_bubble", 0.0)
    comp = phases.get("compute", 0.0)
    exe = step._cached._jfn.lower(p, opt, tokens, targets, 0).compile()
    cost = profiler.cost_from_executable(step.jit_key, exe)
    ma = exe.memory_analysis()
    out[sched] = {{
        "step_ms": best * 1e3,
        "bubble_grid": step.bubble_fraction,
        "bubble_measured": bub / (bub + comp) if (bub + comp) else None,
        "peak_bytes": cost.get("peak_bytes"),
        "temp_bytes": getattr(ma, "temp_size_in_bytes", None),
    }}
profiler.attribution_enable(prev)
print(json.dumps(out))
"""


def bench_composed_1f1b():
    """Pipeline-schedule row: the composed-parallel train step racing
    GPipe, 1F1B, interleaved (v=2 virtual chunks) and ZB-H1 zero-bubble
    at fixed geometry (S=4 stages, M=8 microbatches,
    dp2 x pp4) in a fresh subprocess with 8 forced host devices. Step
    time on CPU is a tie by construction (one sequential XLA program
    either way) — the metrics that carry the row are the bubble
    fractions (schedule-grid analytic and the attributed pp_bubble
    phase) and peak live memory from the compiler's memory_analysis():
    1F1B+remat holds at most 2(S-1)+1 in-flight stage activations where
    GPipe holds all M. CPU-pinned, so the row publishes even when the
    accelerator is unreachable. Returns {schedule: {step_ms,
    bubble_grid, bubble_measured, peak_bytes, temp_bytes}}."""
    import os
    import subprocess
    xla = os.environ.get("XLA_FLAGS", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(xla +
                          " --xla_force_host_platform_device_count=8")
               .strip())
    script = _COMPOSED_1F1B_SCRIPT.format(
        repo=os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=900)
    if r.returncode != 0:
        raise RuntimeError(f"composed-1f1b subprocess failed: "
                           f"{(r.stderr or '').strip()[-500:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def bench_decode(streams=16, slots=4):
    """Decode serving row: CONTINUOUS batching (iteration-level
    admit/retire over the fixed slot batch + paged KV-cache) against
    REQUEST-level batching (a wave of `slots` streams runs to
    completion before the next wave is admitted) on the SAME predictor
    and executables. Streams have deliberately ragged lengths — that is
    where request-level batching bleeds: every wave is held hostage by
    its longest member while continuous batching refills freed slots on
    the very next step. Reports tokens/s for both, TTFT p50/p99, the
    prefill-vs-decode step split, and KV page pool high water.
    Geometry is toy-small: the row measures the scheduler, not the
    model, and must produce numbers on CPU rounds."""
    from incubator_mxnet_tpu.serve import DecodePredictor, DecodeScheduler
    pred = DecodePredictor.toy(slots=slots, page_size=4, num_pages=64,
                               max_pages_per_seq=16)
    pred.warmup()
    prompts = [[1 + i % 13, 2 + i % 7, 3 + i % 5] for i in range(streams)]
    lens = [4 + 8 * (i % 4) for i in range(streams)]    # 4..28 tokens

    def continuous():
        sched = DecodeScheduler(pred, max_queue=streams + 4,
                                name="bench-decode")
        sched.start()
        try:
            t0 = time.perf_counter()
            sts = [sched.submit(p, max_new_tokens=n)
                   for p, n in zip(prompts, lens)]
            toks = sum(len(st.result(timeout=600)) for st in sts)
            wall = time.perf_counter() - t0
            snap = sched.stats.snapshot()
            hw = sched.allocator.high_water
        finally:
            sched.stop()
        return toks / wall, snap, hw

    def request_level():
        sched = DecodeScheduler(pred, max_queue=streams + 4,
                                name="bench-decode-req")
        sched.start()
        try:
            t0 = time.perf_counter()
            toks = 0
            for w in range(0, streams, slots):
                sts = [sched.submit(p, max_new_tokens=n)
                       for p, n in zip(prompts[w:w + slots],
                                       lens[w:w + slots])]
                toks += sum(len(st.result(timeout=600)) for st in sts)
            wall = time.perf_counter() - t0
        finally:
            sched.stop()
        return toks / wall

    # warm both paths once (first stream pays dispatch warmup overheads)
    continuous()
    cont_tok_s, snap, high_water = continuous()
    req_tok_s = request_level()
    return {"cont_tok_s": cont_tok_s, "req_tok_s": req_tok_s,
            "ttft_p50_ms": snap["ttft_p50_ms"],
            "ttft_p99_ms": snap["ttft_p99_ms"],
            "prefill_p50_ms": snap["prefill_p50_ms"],
            "decode_step_p50_ms": snap["decode_step_p50_ms"],
            "kv_high_water": high_water, "kv_total": pred.num_pages}


def bench_disagg_serve(requests=12, prefix_len=24, suffix_len=4,
                       new_tokens=12, budget=64):
    """Disaggregated-serving row: a shared-prefix workload (every
    request repeats one long prompt prefix, production multi-turn/
    system-prompt traffic) raced DISAGGREGATED (dedicated prefill
    engine + prefix cache + real KV-page shipping through a local
    coordinator, then kv_import admission on a decode scheduler)
    against the PR-13 COLOCATED scheduler, at EQUAL total page budget
    (the disagg side splits it between the prefill pool and the decode
    pool). The colocated side recomputes the shared prefix per request
    inside the decode replica; the disagg side computes it once, serves
    the rest from the prefix cache, and the decode pool never spends a
    step on prompt math. TTFT is measured CLIENT-side (request start to
    first token) so the prefill leg is charged honestly. Returns
    {colocated: {...}, disagg: {...}, prefix_cache_hit_rate,
    pages_shipped, bytes_shipped}."""
    from concurrent.futures import ThreadPoolExecutor
    from incubator_mxnet_tpu.serve import DecodePredictor, DecodeScheduler
    from incubator_mxnet_tpu.serve import disagg as _disagg
    from incubator_mxnet_tpu.serve.disagg import (PrefillEngine,
                                                  fetch_kv_import,
                                                  ship_key_for)
    from incubator_mxnet_tpu.kvstore_server import (connect_async_server,
                                                    start_async_server)

    prefix = [1 + (i % 13) for i in range(prefix_len)]
    prompts = [prefix + [2 + ((i + j) % 11) for j in range(suffix_len)]
               for i in range(requests)]
    geom = dict(slots=4, page_size=4, max_pages_per_seq=16,
                prompt_buckets=(8, 16, 32))

    def run_fleet(submit_one):
        """Drive all requests through `submit_one(prompt) -> stream`,
        measuring client-side TTFT per request + aggregate tok/s."""
        ttfts, total = [], 0
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=4) as pool:
            def one(p):
                ts = time.perf_counter()
                st = submit_one(p)
                n, first = 0, None
                for _ in st:
                    if first is None:
                        first = time.perf_counter() - ts
                    n += 1
                return first, n
            for first, n in pool.map(one, prompts):
                ttfts.append(first * 1e3)
                total += n
        wall = time.perf_counter() - t0
        ttfts.sort()
        return {"tok_s": total / wall,
                "ttft_p50_ms": ttfts[len(ttfts) // 2],
                "ttft_p99_ms": ttfts[min(len(ttfts) - 1,
                                         int(len(ttfts) * 0.99))]}

    # -- colocated baseline: one scheduler owns the whole budget -------
    pred_co = DecodePredictor.toy(num_pages=budget, **geom)
    pred_co.warmup()
    sched = DecodeScheduler(pred_co, max_queue=requests + 4,
                            name="bench-disagg-co")
    sched.start()
    try:
        run_fleet(lambda p: sched.submit(p, max_new_tokens=new_tokens))
        colocated = run_fleet(
            lambda p: sched.submit(p, max_new_tokens=new_tokens))
    finally:
        sched.stop()

    # -- disaggregated: budget split prefill pool / decode pool --------
    pred_pre = DecodePredictor.toy(num_pages=budget // 2, slots=1,
                                   page_size=4, max_pages_per_seq=16,
                                   prompt_buckets=(8, 16, 32))
    pred_dec = DecodePredictor.toy(num_pages=budget // 2, **geom)
    pred_dec.warmup()
    engine = PrefillEngine(pred_pre, prefix_cache=True)
    engine.warmup()
    dsched = DecodeScheduler(pred_dec, max_queue=requests + 4,
                             name="bench-disagg")
    dsched.start()
    coord = start_async_server()
    cli = connect_async_server(coord)
    _disagg.clear()
    seq = iter(range(10 ** 9))

    def disagg_submit(p):
        export = engine.run(p)
        key = ship_key_for("bench", str(next(seq)))
        engine.ship(cli, key, export)
        imp = fetch_kv_import(cli, key)
        return dsched.submit(p, max_new_tokens=new_tokens, kv_import=imp)

    try:
        run_fleet(disagg_submit)
        engine.prefix_cache.clear()
        disagg = run_fleet(disagg_submit)
        cache = engine.prefix_cache.stats()
        ship = _disagg.stats()
    finally:
        dsched.stop()
        cli.close()
    return {"colocated": colocated, "disagg": disagg,
            "prefix_cache_hit_rate": cache["hit_rate"],
            "prefix_tokens_saved": cache["tokens_saved"],
            "pages_shipped": ship.get("pages_shipped", 0),
            "bytes_shipped": ship.get("bytes_shipped", 0)}


def bench_spec_decode(streams=16, slots=4):
    """Speculative-decoding row: the SAME ragged stream set run through
    plain continuous decode (PR-13 path, one dispatch per token) and
    through draft-propose / batched-verify speculation at k=2 and k=4
    (serve/spec_decode.py: one fixed-shape verify dispatch covers up to
    k+1 tokens per stream per iteration). Greedy acceptance keeps the
    emitted streams bit-identical, so the ONLY thing this row can
    measure is dispatch amortization — which is exactly the speculation
    win and is visible on CPU rounds. Reports tok/s for all three,
    accept-rate mean, and TTFT + inter-token p50/p99 per variant."""
    from incubator_mxnet_tpu.serve import DecodePredictor, DecodeScheduler
    prompts = [[1 + i % 13, 2 + i % 7, 3 + i % 5] for i in range(streams)]
    lens = [12 + 8 * (i % 4) for i in range(streams)]    # 12..36 tokens

    def run(spec_k):
        pred = DecodePredictor.toy(slots=slots, page_size=4, num_pages=64,
                                   max_pages_per_seq=16)
        pred.warmup()
        sched = DecodeScheduler(pred, max_queue=streams + 4,
                                spec_decode=spec_k is not None,
                                spec_k=spec_k,
                                name=f"bench-spec-k{spec_k or 0}")
        sched.start()
        try:
            def wave():
                t0 = time.perf_counter()
                sts = [sched.submit(p, max_new_tokens=n)
                       for p, n in zip(prompts, lens)]
                out = [st.result(timeout=600) for st in sts]
                wall = time.perf_counter() - t0
                return sum(len(t) for t in out) / wall, out
            wave()          # first wave pays dispatch warmup overheads
            tok_s, toks = wave()
            snap = sched.stats.snapshot()
        finally:
            sched.stop()
        return tok_s, toks, snap

    plain_tok_s, plain_toks, plain_snap = run(None)
    row = {"plain_tok_s": plain_tok_s,
           "plain_ttft_p50_ms": plain_snap["ttft_p50_ms"],
           "plain_ttft_p99_ms": plain_snap["ttft_p99_ms"],
           "plain_token_p50_ms": plain_snap["token_p50_ms"],
           "plain_token_p99_ms": plain_snap["token_p99_ms"]}
    for k in (2, 4):
        tok_s, toks, snap = run(k)
        row[f"spec_k{k}"] = {
            "tok_s": tok_s,
            "speedup": tok_s / plain_tok_s if plain_tok_s else None,
            "bit_identical": toks == plain_toks,
            "accept_rate": snap["spec_accept_rate_mean"],
            "adaptive_k": snap["spec_adaptive_k"],
            "ttft_p50_ms": snap["ttft_p50_ms"],
            "ttft_p99_ms": snap["ttft_p99_ms"],
            "token_p50_ms": snap["token_p50_ms"],
            "token_p99_ms": snap["token_p99_ms"],
            "verify_p50_ms": snap["spec_verify_p50_ms"]}
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=None,
                    help="timed steps (default: per-config on TPU — enough "
                         "to amortize the tunnel dispatch + loop entry to "
                         "<2%% of the measurement; 3 on CPU)")
    ap.add_argument("--full", action="store_true",
                    help="run every config, not just the headline")
    args = ap.parse_args()

    platform = _wait_for_backend()
    if platform is None:
        print("[bench] BACKEND UNAVAILABLE: no usable jax backend within "
              "the init deadline (tunnel down?); set "
              "MXTPU_BENCH_INIT_TIMEOUT to wait longer", file=sys.stderr)
        print(json.dumps({"metric": "resnet50_train_b32_fp32_img_per_sec",
                          "value": None, "unit": "img/s",
                          "vs_baseline": None,
                          "error": "backend_unavailable"}), flush=True)
        return 2
    import os
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # a site plugin may have force-registered the tunnel platform;
        # the explicit config update makes the env var win (same dance
        # as tests/conftest.py)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    platform = jax.devices()[0].platform
    kind, peak = _device_peak()
    on_tpu = platform == "tpu"

    def steps_for(mode, dtype):
        """Steps per compiled loop: long enough that the remote-dispatch
        RPC (~200ms) and one-time loop entry are noise. Steady-state
        throughput is the metric, matching the reference's hundreds-of-
        batches benchmark loops (example/image-classification/
        benchmark_score.py score(..., max_iter))."""
        if args.steps:
            return args.steps
        if not on_tpu:
            return 3
        if mode == "inference":
            return 400
        return 240 if dtype == "bfloat16" else 60

    configs = [("train", 32, "float32")]
    if args.full or on_tpu:
        configs += [("train", 32, "bfloat16"),
                    ("train", 128, "float32"),
                    ("train", 128, "bfloat16"),
                    ("inference", 32, "float32"),
                    ("inference", 32, "bfloat16"),
                    ("inference", 32, "int8")]

    results = []
    head_printed = False
    for mode, batch, dtype in configs:
        extras = {}
        try:
            if dtype == "int8":
                ips = bench_int8_inference(batch, steps_for(mode, dtype))
            else:
                fn = bench_train if mode == "train" else bench_inference
                ips, extras = fn(batch, dtype, steps_for(mode, dtype))
        except Exception as e:  # OOM on small chips must not kill the run
            print(f"[bench] {mode} b{batch} {dtype}: FAILED {e!r}",
                  file=sys.stderr)
            continue
        flops = RESNET50_FWD_GFLOP * 1e9 * (3.0 if mode == "train" else 1.0)
        cfg_peak = peak * 2 if (peak and dtype == "int8") else peak
        # MFU from the compiler's cost model when it reported; the analytic
        # constant stays as the cross-check row
        cf_step = extras.get("compiler_flops_per_step")
        cf_img = cf_step / batch if cf_step else None
        mfu_analytic = (ips * flops / cfg_peak) if cfg_peak else None
        mfu = (ips * cf_img / cfg_peak) if (cfg_peak and cf_img) \
            else mfu_analytic
        base = BASELINES.get((mode, batch, dtype))
        results.append({"mode": mode, "batch": batch, "dtype": dtype,
                        "img_per_sec": round(ips, 2),
                        "mfu": round(mfu, 4) if mfu is not None else None,
                        "mfu_analytic": round(mfu_analytic, 4)
                        if mfu_analytic is not None else None,
                        "compiler_gflop_per_img": round(cf_img / 1e9, 3)
                        if cf_img else None,
                        "phase_ms": extras.get("phase_ms") or None,
                        "vs_baseline": round(ips / base, 3) if base else None})
        print(f"[bench] {mode:9s} b{batch:<4d} {dtype:8s} "
              f"{ips:9.2f} img/s"
              + (f"  MFU {mfu*100:5.1f}%" if mfu is not None else "")
              + (f"  {ips/base:5.2f}x baseline" if base else "")
              + ("  phases " + " ".join(
                  f"{k}={v:.1f}ms" for k, v in
                  sorted(extras["phase_ms"].items(), key=lambda kv: -kv[1]))
                 if extras.get("phase_ms") else ""),
              file=sys.stderr)
        # the 10% compiler-vs-analytic cross-check on the ResNet rows:
        # strict where cost_analysis is authoritative (TPU), warn on CPU.
        # int8 is excluded — the quantized graph is not the 4.09-GFLOP conv
        # stack the analytic constant models.
        if dtype != "int8":
            _check_flops_agreement(f"resnet {mode} b{batch} {dtype}",
                                   flops, cf_img, strict=on_tpu)
        # the headline config runs FIRST; emit its JSON line immediately so
        # an outer timeout on the remaining configs can't swallow the result
        if not head_printed and (mode, batch, dtype) == ("train", 32, "float32"):
            print(json.dumps({
                "metric": "resnet50_train_b32_fp32_img_per_sec",
                "value": results[-1]["img_per_sec"], "unit": "img/s",
                "vs_baseline": results[-1]["vs_baseline"]}), flush=True)
            head_printed = True

    if args.full or on_tpu:
        # BASELINE configs 3 + 4: every workload family in BASELINE.json
        # now has a bench row (LeNet/ResNet via train/inference above,
        # distributed via tools/bandwidth)
        try:
            tok_s = bench_lstm_ptb(steps_for("train", "float32"))
            results.append({"mode": "lstm_ptb_train", "batch": 32,
                            "dtype": "float32",
                            "tokens_per_sec": round(tok_s, 1),
                            "vs_baseline": None})
            print(f"[bench] lstm word-lm (2x200, bptt 35, b32) "
                  f"{tok_s:9.0f} tok/s", file=sys.stderr)
        except Exception as e:
            print(f"[bench] lstm_ptb: FAILED {e!r}", file=sys.stderr)
        try:
            ips = bench_ssd_detection(steps_for("train", "float32"))
            results.append({"mode": "ssd_detection_train", "batch": 8,
                            "dtype": "float32",
                            "img_per_sec": round(ips, 2),
                            "vs_baseline": None})
            print(f"[bench] ssd detection train (multibox stack, b8) "
                  f"{ips:9.2f} img/s", file=sys.stderr)
        except Exception as e:
            print(f"[bench] ssd_detection: FAILED {e!r}", file=sys.stderr)
        try:
            f_sps, u_sps, f_d, u_d = bench_fused_step(
                steps_for("train", "float32"))
            results.append({"mode": "fused_eager_step", "batch": 64,
                            "dtype": "float32",
                            "fused_steps_per_sec": round(f_sps, 2),
                            "unfused_steps_per_sec": round(u_sps, 2),
                            "dispatches_fused": f_d,
                            "dispatches_unfused": u_d,
                            "speedup": round(f_sps / u_sps, 3)
                            if u_sps else None,
                            "vs_baseline": None})
            print(f"[bench] fused eager step (64 params)     "
                  f"{f_sps:9.2f} step/s ({f_d} dispatches) vs "
                  f"{u_sps:9.2f} unfused ({u_d}): "
                  f"{f_sps / u_sps:5.2f}x", file=sys.stderr)
        except Exception as e:
            print(f"[bench] fused_step: FAILED {e!r}", file=sys.stderr)
        try:
            s_ips, p_ips = bench_input_pipeline(
                steps_for("train", "float32"))
            results.append({"mode": "input_pipeline", "batch": 32,
                            "dtype": "float32",
                            "sync_img_per_sec": round(s_ips, 2),
                            "prefetch_img_per_sec": round(p_ips, 2),
                            "speedup": round(p_ips / s_ips, 3)
                            if s_ips else None,
                            "vs_baseline": None})
            print(f"[bench] input pipeline (b32)            "
                  f"{p_ips:9.2f} img/s prefetched vs "
                  f"{s_ips:9.2f} sync: {p_ips / s_ips:5.2f}x",
                  file=sys.stderr)
        except Exception as e:
            print(f"[bench] input_pipeline: FAILED {e!r}", file=sys.stderr)
        try:
            fb_f, fb_u = bench_fused_block(steps_for("train", "float32"))
            results.append({"mode": "fused_block_train", "batch": 16,
                            "dtype": "float32",
                            "fused_img_per_sec": round(fb_f, 2),
                            "unfused_img_per_sec": round(fb_u, 2),
                            "speedup": round(fb_f / fb_u, 3)
                            if fb_u else None,
                            "vs_baseline": None})
            print(f"[bench] fused block train (resnet18, b16) "
                  f"{fb_f:9.2f} img/s fused vs {fb_u:9.2f} unfused: "
                  f"{fb_f / fb_u:5.2f}x", file=sys.stderr)
        except Exception as e:
            print(f"[bench] fused_block: FAILED {e!r}", file=sys.stderr)

    # cold-start row runs in EVERY mode: it is CPU-pinned (measures the
    # executable cache, not the chip) and cheap, and it must publish even
    # on rounds where the accelerator is unreachable
    try:
        cold, warm = bench_serve_cold_start()
        speedup = (cold["ttfp_ms"] / warm["ttfp_ms"]
                   if warm["ttfp_ms"] else None)
        results.append({"mode": "serve_cold_start", "batch": 4,
                        "dtype": "float32",
                        "cold_ttfp_ms": round(cold["ttfp_ms"], 1),
                        "warm_ttfp_ms": round(warm["ttfp_ms"], 1),
                        "cold_compile_wall_ms":
                            round(cold["compile_wall_ms"], 1),
                        "warm_compile_wall_ms":
                            round(warm["compile_wall_ms"], 1),
                        "warm_misses": warm["misses"],
                        "warm_disk_hits": warm["disk_hits"],
                        "speedup": round(speedup, 2) if speedup else None,
                        "vs_baseline": None})
        print(f"[bench] serve cold-start (cpu, 4 buckets) TTFP "
              f"{cold['ttfp_ms']:7.0f} ms cold-dir vs "
              f"{warm['ttfp_ms']:7.0f} ms warm-dir: {speedup:5.2f}x "
              f"({warm['disk_hits']} deserialized, "
              f"{warm['misses']} recompiled)", file=sys.stderr)
    except Exception as e:
        print(f"[bench] serve_cold_start: FAILED {e!r}", file=sys.stderr)

    # decode-serving row also runs in EVERY mode: the continuous-vs-
    # request-level gap is a scheduler property, visible on CPU too
    try:
        dec = bench_decode()
        gain = (dec["cont_tok_s"] / dec["req_tok_s"]
                if dec["req_tok_s"] else None)
        results.append({"mode": "decode_serve", "batch": 16,
                        "dtype": "float32",
                        "continuous_tok_per_sec":
                            round(dec["cont_tok_s"], 1),
                        "request_level_tok_per_sec":
                            round(dec["req_tok_s"], 1),
                        "ttft_p50_ms": dec["ttft_p50_ms"],
                        "ttft_p99_ms": dec["ttft_p99_ms"],
                        "prefill_p50_ms": dec["prefill_p50_ms"],
                        "decode_step_p50_ms": dec["decode_step_p50_ms"],
                        "kv_pages_high_water": dec["kv_high_water"],
                        "kv_pages_total": dec["kv_total"],
                        "speedup": round(gain, 2) if gain else None,
                        "vs_baseline": None})
        print(f"[bench] decode continuous (16 streams, 4 slots) "
              f"{dec['cont_tok_s']:7.1f} tok/s vs request-level "
              f"{dec['req_tok_s']:7.1f}: {gain:5.2f}x  TTFT p50 "
              f"{dec['ttft_p50_ms']:.1f}/p99 {dec['ttft_p99_ms']:.1f} ms  "
              f"prefill {dec['prefill_p50_ms']:.1f} ms, step "
              f"{dec['decode_step_p50_ms']:.1f} ms  KV peak "
              f"{dec['kv_high_water']}/{dec['kv_total']} pages",
              file=sys.stderr)
    except Exception as e:
        print(f"[bench] decode_serve: FAILED {e!r}", file=sys.stderr)

    # disaggregated-serving row also runs in EVERY mode: the shared-
    # prefix win (prefill once + cache + ship vs recompute per request)
    # is a scheduler/cache property, visible on CPU too
    try:
        dg = bench_disagg_serve()
        co, ds = dg["colocated"], dg["disagg"]
        gain = ds["tok_s"] / co["tok_s"] if co["tok_s"] else None
        results.append({"mode": "disagg_serve", "batch": 12,
                        "dtype": "float32",
                        "disagg_tok_per_sec": round(ds["tok_s"], 1),
                        "colocated_tok_per_sec": round(co["tok_s"], 1),
                        "disagg_ttft_p50_ms": round(ds["ttft_p50_ms"], 1),
                        "disagg_ttft_p99_ms": round(ds["ttft_p99_ms"], 1),
                        "colocated_ttft_p50_ms":
                            round(co["ttft_p50_ms"], 1),
                        "colocated_ttft_p99_ms":
                            round(co["ttft_p99_ms"], 1),
                        "prefix_cache_hit_rate":
                            round(dg["prefix_cache_hit_rate"], 3),
                        "prefix_tokens_saved": dg["prefix_tokens_saved"],
                        "pages_shipped": dg["pages_shipped"],
                        "bytes_shipped": dg["bytes_shipped"],
                        "speedup": round(gain, 2) if gain else None,
                        "vs_baseline": None})
        print(f"[bench] disagg serve (12 shared-prefix streams, equal "
              f"page budget) {ds['tok_s']:7.1f} tok/s vs colocated "
              f"{co['tok_s']:7.1f}: {gain:5.2f}x  TTFT p50 "
              f"{ds['ttft_p50_ms']:.1f}/p99 {ds['ttft_p99_ms']:.1f} ms  "
              f"cache hit {dg['prefix_cache_hit_rate']*100:.0f}%  "
              f"{dg['pages_shipped']} pages "
              f"({dg['bytes_shipped']} B) shipped", file=sys.stderr)
    except Exception as e:
        print(f"[bench] disagg_serve: FAILED {e!r}", file=sys.stderr)

    # speculative-decoding row also runs in EVERY mode: the dispatch
    # amortization of one batched verify per k+1 tokens is a scheduler
    # property, visible on CPU too (greedy keeps streams bit-identical)
    try:
        sd = bench_spec_decode()
        k2, k4 = sd["spec_k2"], sd["spec_k4"]
        results.append({"mode": "spec_decode", "batch": 16,
                        "dtype": "float32",
                        "plain_tok_per_sec": round(sd["plain_tok_s"], 1),
                        "spec_k2_tok_per_sec": round(k2["tok_s"], 1),
                        "spec_k4_tok_per_sec": round(k4["tok_s"], 1),
                        "spec_k2_speedup": round(k2["speedup"], 2),
                        "spec_k4_speedup": round(k4["speedup"], 2),
                        "spec_k2_accept_rate": round(k2["accept_rate"], 3),
                        "spec_k4_accept_rate": round(k4["accept_rate"], 3),
                        "bit_identical": bool(k2["bit_identical"]
                                              and k4["bit_identical"]),
                        "ttft_p50_ms": k4["ttft_p50_ms"],
                        "ttft_p99_ms": k4["ttft_p99_ms"],
                        "token_p50_ms": k4["token_p50_ms"],
                        "token_p99_ms": k4["token_p99_ms"],
                        "verify_p50_ms": k4["verify_p50_ms"],
                        "speedup": round(k4["speedup"], 2),
                        "vs_baseline": None})
        print(f"[bench] spec decode (16 streams, 4 slots) plain "
              f"{sd['plain_tok_s']:7.1f} tok/s vs k=2 "
              f"{k2['tok_s']:7.1f} ({k2['speedup']:4.2f}x) vs k=4 "
              f"{k4['tok_s']:7.1f} ({k4['speedup']:4.2f}x)  accept "
              f"{k4['accept_rate']*100:.0f}%  identical="
              f"{bool(k2['bit_identical'] and k4['bit_identical'])}  "
              f"token p50 {k4['token_p50_ms']:.1f}/p99 "
              f"{k4['token_p99_ms']:.1f} ms", file=sys.stderr)
    except Exception as e:
        print(f"[bench] spec_decode: FAILED {e!r}", file=sys.stderr)

    # checkpoint-overhead row also runs in EVERY mode: it measures the
    # step-path cost of fault tolerance (host snapshot + write-behind),
    # which matters on CPU rounds exactly as much as on TPU rounds
    try:
        ck_steps = max(200, steps_for("train", "float32"))
        b_sps, s_sps, a_sps = bench_checkpoint(ck_steps)
        sync_pct = (100.0 * (b_sps / s_sps - 1.0)) if s_sps else None
        async_pct = (100.0 * (b_sps / a_sps - 1.0)) if a_sps else None
        results.append({"mode": "checkpoint", "batch": 32,
                        "dtype": "float32",
                        "base_steps_per_sec": round(b_sps, 2),
                        "sync_steps_per_sec": round(s_sps, 2),
                        "async_steps_per_sec": round(a_sps, 2),
                        "sync_overhead_pct": round(sync_pct, 2)
                        if sync_pct is not None else None,
                        "async_overhead_pct": round(async_pct, 2)
                        if async_pct is not None else None,
                        "vs_baseline": None})
        print(f"[bench] checkpoint overhead (mlp 4x512, every 100 steps) "
              f"async {async_pct:+6.2f}% vs sync {sync_pct:+6.2f}% "
              f"of step time", file=sys.stderr)
    except Exception as e:
        print(f"[bench] checkpoint: FAILED {e!r}", file=sys.stderr)

    # pipeline-schedule row also runs in EVERY mode: the 1F1B-vs-GPipe
    # bubble and memory gap is a schedule property, measured in grid
    # ticks and compiler memory accounting inside a CPU-pinned
    # subprocess (8 forced host devices)
    try:
        pr = bench_composed_1f1b()
        g, f = pr["gpipe"], pr["1f1b"]
        mem_ratio = (g["temp_bytes"] / f["temp_bytes"]
                     if g.get("temp_bytes") and f.get("temp_bytes")
                     else None)
        row = {"mode": "composed_1f1b", "batch": 16,
               "dtype": "float32",
               "stages": 4, "microbatches": 8,
               "gpipe_step_ms": round(g["step_ms"], 1),
               "pp1f1b_step_ms": round(f["step_ms"], 1),
               "gpipe_bubble": g["bubble_grid"],
               "pp1f1b_bubble": f["bubble_grid"],
               "pp1f1b_bubble_measured":
                   round(f["bubble_measured"], 4)
                   if f.get("bubble_measured") is not None
                   else None,
               "gpipe_peak_bytes": g.get("peak_bytes"),
               "pp1f1b_peak_bytes": f.get("peak_bytes"),
               "gpipe_temp_bytes": g.get("temp_bytes"),
               "pp1f1b_temp_bytes": f.get("temp_bytes"),
               "mem_reduction": round(mem_ratio, 2)
               if mem_ratio else None,
               "vs_baseline": None}
        # the zero-bubble frontier: interleaved v=2 and ZB-H1 ride the
        # same subprocess; measured bubble must equal the grid analytic
        for name, key in (("interleaved", "interleaved"), ("zb1", "zb1")):
            e = pr.get(key)
            if not e:
                continue
            row[f"{name}_step_ms"] = round(e["step_ms"], 1)
            row[f"{name}_bubble"] = e["bubble_grid"]
            row[f"{name}_bubble_measured"] = (
                round(e["bubble_measured"], 4)
                if e.get("bubble_measured") is not None else None)
            row[f"{name}_peak_bytes"] = e.get("peak_bytes")
            row[f"{name}_temp_bytes"] = e.get("temp_bytes")
        go = pr.get("gpipe_offload")
        if go:
            row["offload_temp_bytes"] = go.get("temp_bytes")
        results.append(row)
        z = pr.get("zb1", {})
        print(f"[bench] composed pipeline (S=4, M=8, dp2xpp4) bubble "
              f"{g['bubble_grid']:.3f} gpipe / {f['bubble_grid']:.3f} "
              f"1f1b / "
              f"{pr.get('interleaved', {}).get('bubble_grid', -1):.3f} "
              f"interleaved(v2) / {z.get('bubble_grid', -1):.3f} zb1  "
              f"step {f['step_ms']:7.1f} ms (cpu)"
              + (f"  temp mem {mem_ratio:4.2f}x smaller with remat"
                 if mem_ratio else ""), file=sys.stderr)
    except Exception as e:
        print(f"[bench] composed_1f1b: FAILED {e!r}", file=sys.stderr)

    if on_tpu:
        try:
            tok_s, tmfu = bench_transformer()
            results.append({"mode": "transformer_train", "batch": 32,
                            "dtype": "bfloat16",
                            "tokens_per_sec": round(tok_s, 1),
                            "mfu": round(tmfu, 4) if tmfu else None,
                            "vs_baseline": None})
            print(f"[bench] transformer train (12x1024, seq 2048, bf16) "
                  f"{tok_s:9.0f} tok/s  MFU {tmfu*100:5.1f}%",
                  file=sys.stderr)
        except Exception as e:
            print(f"[bench] transformer: FAILED {e!r}", file=sys.stderr)
        try:
            ltok, lt = bench_transformer_longctx()
            results.append({"mode": "transformer_train_longctx",
                            "batch": 4, "dtype": "bfloat16",
                            "seq_len": lt,
                            "tokens_per_sec": round(ltok, 1),
                            "vs_baseline": None})
            print(f"[bench] transformer long-context (seq {lt}, flash "
                  f"fwd+bwd kernels) {ltok:9.0f} tok/s  "
                  f"(XLA attention: OOM at this shape)", file=sys.stderr)
        except Exception as e:
            print(f"[bench] transformer longctx: FAILED {e!r}",
                  file=sys.stderr)

    try:
        from incubator_mxnet_tpu import tune as _tune
        ts = _tune.stats()
        if any(ts.values()):
            results.append(dict({"mode": "tune_stats"}, **ts))
            print("[bench] tune: " +
                  " ".join(f"{k}={v}" for k, v in sorted(ts.items())),
                  file=sys.stderr)
    except Exception as e:
        print(f"[bench] tune stats: FAILED {e!r}", file=sys.stderr)

    print(f"[bench] device: {kind} ({platform}), timed steps: "
          f"{args.steps or 'per-config'}", file=sys.stderr)
    print("[bench] all: " + json.dumps(results), file=sys.stderr)

    if not head_printed:
        print(json.dumps({"metric": "resnet50_train_b32_fp32_img_per_sec",
                          "value": None, "unit": "img/s",
                          "vs_baseline": None}))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
