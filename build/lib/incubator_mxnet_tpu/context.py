"""Device contexts: cpu / gpu / tpu.

Reference: include/mxnet/base.h:102 `struct Context` with DeviceType
{kCPU=1, kGPU=2, kCPUPinned=3, kCPUShared=5} (base.h:105-108) and
python/mxnet/context.py:327 (`cpu()/gpu()/cpu_pinned()`, default-ctx stack).

TPU-native redesign: a Context is a named view onto a `jax.Device`. `tpu()` is
first-class (the reference's north-star `kTPU` device type). Device placement
is realized with `jax.device_put` / sharding rather than per-device storage
managers — XLA owns HBM (reference src/storage/ is subsumed by the XLA
allocator, see SURVEY.md §7 translation table).
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "cpu_shared",
           "current_context", "num_gpus", "num_tpus", "gpu_memory_info"]


class DeviceType:
    kCPU = 1
    kGPU = 2
    kCPUPinned = 3
    kCPUShared = 5
    kTPU = 6


_DEVTYPE_NAME = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
_NAME_DEVTYPE = {v: k for k, v in _DEVTYPE_NAME.items()}

# jax platform names that count as each device kind. "axon" is the tunneled
# TPU platform; "tpu" the standard one; "gpu"/"cuda"/"rocm" for GPU backends.
_TPU_PLATFORMS = ("tpu", "axon")
_GPU_PLATFORMS = ("gpu", "cuda", "rocm")


class _TLS(threading.local):
    def __init__(self):
        super().__init__()
        self.stack = []


_tls = _TLS()


def _jax_devices_for(device_typename: str):
    import jax
    plats = {"tpu": _TPU_PLATFORMS, "gpu": _GPU_PLATFORMS}.get(
        device_typename, (device_typename,))
    # local_devices: under a multi-process (pod) runtime jax.devices() is
    # GLOBAL and placing eager arrays on another process's device is
    # invalid — a Context always names a process-local device (the
    # reference's Context is likewise node-local)
    out = []
    for d in jax.local_devices():
        if d.platform.lower() in plats:
            out.append(d)
    if device_typename == "cpu" and not out:
        # default-backend local_devices may be TPU-only; ask the cpu
        # backend for ITS process-local devices (never the global list —
        # placing eager arrays on another process's device is invalid)
        try:
            out = jax.local_devices(backend="cpu")
        except RuntimeError:
            out = [d for d in jax.devices("cpu")
                   if d.process_index == jax.process_index()] or \
                jax.devices("cpu")
    return out


class Context:
    """Device context. Constructing one never touches hardware; `.jax_device`
    resolves lazily (reference Context is likewise a plain (type, id) pair,
    include/mxnet/base.h:158-167)."""

    devtype2str = _DEVTYPE_NAME
    devstr2type = _NAME_DEVTYPE

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_typename, device_type.device_id
        if isinstance(device_type, int):
            device_type = _DEVTYPE_NAME[device_type]
        if device_type not in _NAME_DEVTYPE:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_typename = device_type
        self.device_id = int(device_id)

    @property
    def device_type(self):
        return self.device_typename

    @property
    def _base_typename(self):
        # pinned/shared CPU memory distinctions are host-runtime details of the
        # reference (src/storage/storage.cc:62-120); on the JAX runtime they all
        # map to the host platform.
        n = self.device_typename
        return "cpu" if n.startswith("cpu") else n

    @property
    def jax_device(self):
        devs = _jax_devices_for(self._base_typename)
        if not devs:
            raise MXNetError(f"no {self._base_typename} device available "
                             f"(jax sees: {_platforms()})")
        if self.device_id >= len(devs):
            raise MXNetError(f"{self._base_typename}({self.device_id}) out of range; "
                             f"{len(devs)} device(s) present")
        return devs[self.device_id]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typename == other.device_typename
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_typename, self.device_id))

    def __repr__(self):
        return f"{self.device_typename}({self.device_id})"

    __str__ = __repr__

    def __enter__(self):
        _tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()

    @classmethod
    def default_ctx(cls):
        return current_context()


def _platforms():
    import jax
    return sorted({d.platform for d in jax.devices()})


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """First-class TPU context — the north star of the port
    (reference: BASELINE.json north_star; include/mxnet/base.h would gain kTPU)."""
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_jax_devices_for("gpu"))


def num_tpus() -> int:
    return len(_jax_devices_for("tpu"))


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes; reference python/mxnet/context.py mx.context.gpu_memory_info.
    On TPU/JAX runtimes memory stats come from device.memory_stats()."""
    for name in ("gpu", "tpu"):
        devs = _jax_devices_for(name)
        if devs and device_id < len(devs):
            stats = devs[device_id].memory_stats() or {}
            total = stats.get("bytes_limit", 0)
            used = stats.get("bytes_in_use", 0)
            return (total - used, total)
    raise MXNetError("no accelerator device")


def current_context() -> Context:
    """Default context, settable via `with mx.tpu(0):` (reference
    python/mxnet/context.py:327 default-ctx stack). Out of the box it prefers
    the best available device: tpu > gpu > cpu."""
    if _tls.stack:
        return _tls.stack[-1]
    return _best_context()


_best_cache = None


def _best_context() -> Context:
    global _best_cache
    if _best_cache is None:
        if num_tpus():
            _best_cache = tpu(0)
        elif num_gpus():
            _best_cache = gpu(0)
        else:
            _best_cache = cpu(0)
    return _best_cache
