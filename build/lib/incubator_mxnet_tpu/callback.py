"""Training progress callbacks.

Capability parity with the reference's callback module (throughput
logging, periodic checkpointing, metric echo — python/mxnet/callback.py),
designed differently: throughput is tracked by a monotonic-clock rate
tracker with exponential smoothing, and every emission is a structured
record first — the log line is just one sink for it. `tools/parse_log.py`
consumes the default log format directly (it emits the `Epoch[e] ...
Speed:` / `Train-metric=value` shapes that script scans for).
"""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "LogValidationMetricsCallback", "module_checkpoint"]


class _RateTracker:
    """Windowed samples/sec with an EMA over the window rates.

    Uses `time.monotonic` (wall-clock adjustments — NTP, suspend — must not
    produce negative or absurd rates). One tracker per training run; reset()
    on epoch change keeps windows from spanning the eval gap.
    """

    def __init__(self, smoothing=0.5):
        self.smoothing = float(smoothing)
        self.ema = None
        self._mark = None       # (monotonic_time, batch_index)

    def reset(self, batch=0):
        self._mark = (time.monotonic(), batch)
        return self

    def advance(self, batch, batch_size):
        """Close the window [mark, batch) and open a new one. Returns the
        window's instantaneous rate in samples/sec (inf if the window took
        no measurable time) and updates the EMA."""
        now = time.monotonic()
        if self._mark is None:
            self._mark = (now, batch)
            return None
        t0, b0 = self._mark
        self._mark = (now, batch)
        dt = now - t0
        nsamples = (batch - b0) * batch_size
        rate = nsamples / dt if dt > 0 else float("inf")
        # an unmeasurably-short window reports inf for ITSELF but must not
        # poison the EMA (inf blended with anything stays inf forever)
        if rate != float("inf"):
            if self.ema is None:
                self.ema = rate
            else:
                s = self.smoothing
                self.ema = s * self.ema + (1.0 - s) * rate
        return rate


class Speedometer:
    """Batch-end callback: report throughput (and optionally metrics) every
    `frequent` batches.

    Emits a structured record per report:
        {"epoch", "batch_start", "batch_end", "rate", "ema_rate",
         "metrics": [(name, value), ...]}
    `sink` receives each record; the default sink writes a log line in the
    format `tools/parse_log.py` parses. Same constructor surface as the
    reference's Speedometer, so Module.fit callbacks are drop-in.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True,
                 smoothing=0.5, sink=None):
        self.batch_size = int(batch_size)
        self.frequent = max(1, int(frequent))
        self.auto_reset = auto_reset
        self._tracker = _RateTracker(smoothing)
        self._epoch = None
        self.sink = sink or self._log_sink
        self.records = []        # most-recent reports (bounded)

    @staticmethod
    def _log_sink(rec):
        parts = [f"Epoch[{rec['epoch']}] "
                 f"Batch [{rec['batch_start']}-{rec['batch_end']}]\t"
                 f"Speed: {rec['rate']:.2f} samples/sec"]
        if rec["ema_rate"] is not None and rec["ema_rate"] != rec["rate"]:
            parts.append(f"(ema {rec['ema_rate']:.2f})")
        for name, value in rec["metrics"]:
            parts.append(f"Train-{name}={value:f}")
        logging.info("\t".join(parts))

    def __call__(self, param):
        batch = param.nbatch
        mark = self._tracker._mark
        # fresh epoch, first call, or a restarted batch counter: the old
        # window is meaningless — open a new one at the current batch
        if self._epoch != param.epoch or mark is None or batch < mark[1]:
            self._epoch = param.epoch
            self._tracker.reset(batch)
            return
        if batch % self.frequent or batch == mark[1]:
            return
        window_start = mark[1]
        rate = self._tracker.advance(batch, self.batch_size)
        metrics = []
        if param.eval_metric is not None:
            metrics = list(param.eval_metric.get_name_value())
            if self.auto_reset:
                param.eval_metric.reset_local()
        rec = {"epoch": param.epoch,
               "batch_start": window_start,
               "batch_end": batch,
               "rate": rate,
               "ema_rate": self._tracker.ema,
               "metrics": metrics}
        self.records.append(rec)
        del self.records[:-64]
        self.sink(rec)


def do_checkpoint(prefix, period=1):
    """Epoch-end callback factory: persist symbol+params every `period`
    epochs through model.save_checkpoint (artifact layout matches the
    reference's prefix-epoch.params / prefix-symbol.json convention)."""
    from .model import save_checkpoint

    period = max(1, int(period))

    def _save(epoch, sym, arg, aux):
        if (epoch + 1) % period == 0:
            save_checkpoint(prefix, epoch + 1, sym, arg, aux)

    return _save


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Like do_checkpoint but routed through a Module instance (so trainer
    state can ride along when save_optimizer_states is set)."""
    period = max(1, int(period))

    def _save(epoch, sym=None, arg=None, aux=None):
        if (epoch + 1) % period == 0:
            mod.save_checkpoint(prefix, epoch + 1, save_optimizer_states)

    return _save


def log_train_metric(period, auto_reset=False):
    """Batch-end callback factory: echo the running train metrics every
    `period` batches without any throughput tracking."""
    period = max(1, int(period))

    def _echo(param):
        if param.nbatch % period or param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                         param.epoch, param.nbatch, name, value)
        if auto_reset:
            param.eval_metric.reset_local()

    return _echo


class LogValidationMetricsCallback:
    """Epoch-end callback: echo validation metrics in the Validation-
    name=value shape parse_log.py scans for."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
