"""Evaluation metrics.

Reference: python/mxnet/metric.py (1,779 LoC): EvalMetric registry:
Accuracy/TopK/F1/MCC/Perplexity/MAE/MSE/RMSE/CE/NLL/PearsonR/CustomMetric +
CompositeEvalMetric.
"""
from __future__ import annotations

import math

import numpy as _np

from .base import MXNetError, Registry
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_REG = Registry("metric")


# short names accepted by create() (reference metric.py registers these
# through mx.registry alias lists, e.g. 'acc' for Accuracy)
_ALIASES = {
    "Accuracy": ("acc",),
    "TopKAccuracy": ("top_k_accuracy", "top_k_acc"),
    "CrossEntropy": ("ce",),
    "NegativeLogLikelihood": ("nll_loss",),
    "PearsonCorrelation": ("pearsonr",),
    "MCC": ("mcc",),
}


def register(cls):
    _REG.register(cls, aliases=_ALIASES.get(cls.__name__, ()))
    return cls


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    return _REG.get(metric)(*args, **kwargs)


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if isinstance(labels, NDArray):
        labels = [labels]
    if isinstance(preds, NDArray):
        preds = [preds]
    if len(labels) != len(preds):
        raise MXNetError(f"label/pred count mismatch {len(labels)} vs {len(preds)}")
    return labels, preds


class EvalMetric:
    """Reference metric.py EvalMetric."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def _accumulate(self, metric, count):
        self.sum_metric += metric
        self.num_inst += count
        self.global_sum_metric += metric
        self.global_num_inst += count

    def __str__(self):
        return f"EvalMetric: {dict([self.get_name_value()[0]])}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()
        super().reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int32).reshape(-1)
            label = label.astype(_np.int32).reshape(-1)
            self._accumulate(float((pred == label).sum()), len(label))


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).astype(_np.int32)
            topk = _np.argsort(pred, axis=-1)[:, -self.top_k:]
            hits = (topk == label.reshape(-1, 1)).any(axis=1)
            self._accumulate(float(hits.sum()), len(label))


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        self._tp = self._fp = self._fn = 0.0
        super().reset()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype(_np.int32)
            label = label.reshape(-1).astype(_np.int32)
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1
            self.global_sum_metric = f1
            self.global_num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference metric.py MCC)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self._t = _np.zeros(4)

    def reset(self):
        self._t = _np.zeros(4)
        super().reset()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype(_np.int32)
            label = label.reshape(-1).astype(_np.int32)
            self._t[0] += float(((pred == 1) & (label == 1)).sum())  # tp
            self._t[1] += float(((pred == 1) & (label == 0)).sum())  # fp
            self._t[2] += float(((pred == 0) & (label == 0)).sum())  # tn
            self._t[3] += float(((pred == 0) & (label == 1)).sum())  # fn
            tp, fp, tn, fn = self._t
            denom = math.sqrt(max((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn),
                                  1e-12))
            self.sum_metric = (tp * tn - fp * fn) / denom
            self.num_inst = 1
            self.global_sum_metric = self.sum_metric
            self.global_num_inst = 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        loss, num = 0.0, 0
        for label, pred in zip(labels, preds):
            label = _as_np(label).astype(_np.int64).reshape(-1)
            pred = _as_np(pred).reshape(len(label), -1)
            probs = pred[_np.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss += -_np.log(_np.maximum(1e-10, probs)).sum()
            num += len(label)
        self._accumulate(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._accumulate(float(_np.abs(label - pred).mean()), 1)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._accumulate(float(((label - pred) ** 2).mean()), 1)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(_np.int64)
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label]
            self._accumulate(float((-_np.log(prob + self.eps)).sum()),
                             label.shape[0])


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)
        self.eps = eps


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label).ravel(), _as_np(pred).ravel()
            r = _np.corrcoef(label, pred)[0, 1]
            self._accumulate(float(r), 1)


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = float(_as_np(pred).sum())
            self._accumulate(loss, _as_np(pred).size)


class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = getattr(feval, "__name__", "custom")
            if name.startswith("<"):
                name = "custom"
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                m, n = reval
                self._accumulate(m, n)
            else:
                self._accumulate(reval, 1)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval as a metric (reference metric.py np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, name, allow_extra_outputs)
