"""Framework RNG: the key chain + `mx.nd.random` sampler API.

Reference: python/mxnet/ndarray/random.py + per-device RNG resource
(src/resource.cc ResourceManagerImpl seeds mshadow Random states;
include/mxnet/random_generator.h parallel RNG).

TPU-native redesign: a process-global jax PRNG key chain, split per sampler
call. `seed()` resets it (reference mx.random.seed seeds every device's
generator). Inside a jit trace (hybridized blocks), the ambient key comes from
a trace-local override installed by the tracer so randomness is reproducible
and trace-safe.
"""
from __future__ import annotations

import threading

import numpy as _np

__all__ = ["seed", "next_key", "uniform", "normal", "randn", "randint", "gamma",
           "exponential", "poisson", "negative_binomial",
           "generalized_negative_binomial", "multinomial", "shuffle"]


class _State(threading.local):
    def __init__(self):
        super().__init__()
        self.key = None
        self.trace_key = None  # set by hybridize tracing
        self.trace_count = 0


_state = _State()


def seed(seed_state: int):
    import jax
    _state.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    import jax
    if _state.trace_key is not None:
        _state.trace_count += 1
        return jax.random.fold_in(_state.trace_key, _state.trace_count)
    if _state.key is None:
        _state.key = jax.random.PRNGKey(_np.random.randint(0, 2**31 - 1))
    _state.key, sub = jax.random.split(_state.key)
    return sub


class _TraceKeyScope:
    """Install a traced key as the ambient RNG source during jit tracing."""

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        self.prev = (_state.trace_key, _state.trace_count)
        _state.trace_key, _state.trace_count = self.key, 0
        return self

    def __exit__(self, *exc):
        _state.trace_key, _state.trace_count = self.prev


def _shape(shape):
    if shape is None:
        return (1,)
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    return invoke("_random_uniform", low=float(low), high=float(high),
                  shape=_shape(shape), dtype=str(dtype or "float32"), out=out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    return invoke("_random_normal", loc=float(loc), scale=float(scale),
                  shape=_shape(shape), dtype=str(dtype or "float32"), out=out)


def randn(*shape, dtype="float32", ctx=None, **kw):
    return normal(0.0, 1.0, shape=shape or (1,), dtype=dtype)


def randint(low, high=None, shape=None, dtype="int32", ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    if high is None:
        low, high = 0, low
    return invoke("_random_randint", low=int(low), high=int(high),
                  shape=_shape(shape), dtype=str(dtype or "int32"), out=out)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    return invoke("_random_gamma", alpha=float(alpha), beta=float(beta),
                  shape=_shape(shape), dtype=str(dtype or "float32"), out=out)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    return invoke("_random_exponential", lam=1.0 / float(scale), shape=_shape(shape),
                  dtype=str(dtype or "float32"), out=out)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    return invoke("_random_poisson", lam=float(lam), shape=_shape(shape),
                  dtype=str(dtype or "float32"), out=out)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    return invoke("_random_negative_binomial", k=int(k), p=float(p),
                  shape=_shape(shape), dtype=str(dtype or "float32"), out=out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype="float32",
                                  ctx=None, out=None, **kw):
    from ..ops.registry import invoke
    return invoke("_random_generalized_negative_binomial", mu=float(mu),
                  alpha=float(alpha), shape=_shape(shape),
                  dtype=str(dtype or "float32"), out=out)


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    from ..ops.registry import invoke
    return invoke("_sample_multinomial", data, shape=tuple(shape) if
                  isinstance(shape, (tuple, list)) else (shape,) if shape else (),
                  get_prob=get_prob, dtype=str(dtype))


def shuffle(data, **kw):
    from ..ops.registry import invoke
    return invoke("_shuffle", data)
