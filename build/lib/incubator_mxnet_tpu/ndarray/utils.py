"""NDArray save/load.

Reference: python/mxnet/ndarray/utils.py:149 save/load over the dmlc::Stream
binary container (MXNDArraySave, include/mxnet/c_api.h:656; impl
src/ndarray/ndarray.cc). The container stores either a list or a str->NDArray
map.

TPU-native redesign: the container is a .npz (numpy zip) with a magic key for
the format version; keys are prefixed `arg:`/`aux:`-style names exactly as the
reference writes them, so Gluon save_parameters/load_parameters round-trips
match. (Sharded/pod-scale checkpoints live in utils/checkpoint.py via orbax.)
"""
from __future__ import annotations

import os
import zipfile

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray

__all__ = ["save", "load", "from_dlpack", "to_dlpack_for_read",
           "to_dlpack_for_write"]

_MAGIC_KEY = "__mxtpu_ndarray_container__"
_LIST_PREFIX = "__list__:"


def save(fname: str, data):
    """Save a list or dict of NDArrays (reference ndarray/utils.py save)."""
    arrays = {}
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        for i, a in enumerate(data):
            if not isinstance(a, NDArray):
                raise MXNetError("save expects NDArrays")
            arrays[f"{_LIST_PREFIX}{i:08d}"] = a.asnumpy()
    elif isinstance(data, dict):
        for k, v in data.items():
            if not isinstance(v, NDArray):
                raise MXNetError("save expects NDArrays")
            arrays[k] = v.asnumpy()
    else:
        raise MXNetError(f"cannot save {type(data)}")
    arrays[_MAGIC_KEY] = _np.asarray([1])
    with open(fname, "wb") as f:
        _np.savez(f, **arrays)


def load(fname: str):
    """Load a container saved by `save` (reference ndarray/utils.py load)."""
    if not os.path.exists(fname):
        raise MXNetError(f"no such file: {fname}")
    with _np.load(fname, allow_pickle=False) as z:
        keys = [k for k in z.files if k != _MAGIC_KEY]
        if keys and all(k.startswith(_LIST_PREFIX) for k in keys):
            return [NDArray(z[k]) for k in sorted(keys)]
        return {k: NDArray(z[k]) for k in keys}


# ---------------------------------------------------------------------------
# DLPack interchange (reference MXNDArrayToDLPack/MXNDArrayFromDLPack,
# include/mxnet/c_api.h; python mxnet.ndarray to_dlpack_for_read/
# to_dlpack_for_write/from_dlpack). jax.Array speaks the dlpack protocol
# natively, so these are thin shims kept for API parity — they are the
# zero-copy bridge to torch/cupy/numpy consumers.
# ---------------------------------------------------------------------------

def from_dlpack(ext):
    """Wrap any object exporting __dlpack__ (torch tensor, numpy array,
    another framework's array) as an NDArray, zero-copy when the producer
    is on a compatible device."""
    import jax.numpy as jnp
    return NDArray(jnp.from_dlpack(ext))


def to_dlpack_for_read(arr):
    """Export an NDArray as a DLPack capsule (read intent; XLA arrays are
    immutable so read/write intent coincide — both names kept for parity).
    Backends without PJRT external-reference support (e.g. tunneled TPU)
    fall back to a host copy's capsule."""
    try:
        return arr._data.__dlpack__()
    except Exception:
        return _np.asarray(arr._data).__dlpack__()


def to_dlpack_for_write(arr):
    """See to_dlpack_for_read — XLA buffers are immutable; a consumer that
    mutates must copy (the reference's write capsule relied on the engine
    write-var lock, which has no XLA analog)."""
    return to_dlpack_for_read(arr)
