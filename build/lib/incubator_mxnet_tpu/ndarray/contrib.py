"""nd.contrib: control-flow sugar + contrib op namespace.

Reference: python/mxnet/ndarray/contrib.py — `foreach`, `while_loop`,
`cond` run imperative Python loops over NDArrays (the symbolic versions
build _foreach/_while_loop/_cond subgraph ops, src/operator/control_flow.cc).

TPU note: outside autograd recording these lower to the registered
`_foreach`/`_while_loop` ops (ops/control_flow_ops.py) — lax.scan-based, so
the XLA program is NOT unrolled and compile time is independent of trip
count. Under autograd.record() the tape needs gradients to flow into arrays
the body *closes over* (not just explicit inputs), so the recorded path is
an unrolled eager loop exactly like the reference's imperative sugar.
"""
from __future__ import annotations

from .. import autograd
from ..base import MXNetError
from .ndarray import NDArray
from ..ops.dgl_ops import (dgl_csr_neighbor_uniform_sample,      # noqa: F401
                           dgl_csr_neighbor_non_uniform_sample,  # noqa: F401
                           dgl_subgraph, edge_id, dgl_adjacency,  # noqa: F401
                           dgl_graph_compact)                     # noqa: F401

__all__ = ["foreach", "while_loop", "cond",
           "dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample", "dgl_subgraph",
           "edge_id", "dgl_adjacency", "dgl_graph_compact"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _trace_errors():
    import jax
    return (jax.errors.ConcretizationTypeError,
            jax.errors.TracerArrayConversionError,
            jax.errors.TracerBoolConversionError,
            jax.errors.TracerIntegerConversionError,
            NotImplementedError, TypeError)


def foreach(body, data, init_states):
    """Scan `body` over axis 0 (reference contrib.py foreach;
    src/operator/control_flow.cc:1089 _foreach).

    body(data_t, states) -> (out_t, new_states)."""
    from . import stack as nd_stack

    data_list = _as_list(data)
    states = _as_list(init_states)
    single_state = not isinstance(init_states, (list, tuple))
    single_data = not isinstance(data, (list, tuple))

    if not autograd.is_recording():
        from ..ops.registry import invoke
        try:
            res = invoke("_foreach", *data_list, *states, body=body,
                         n_data=len(data_list), single_data=single_data,
                         single_state=single_state)
            res = res if isinstance(res, list) else [res]
            n_out = len(res) - len(states)
            outs, fin = res[:n_out], res[n_out:]
            merged = outs[0] if len(outs) == 1 else outs
            return merged, (fin[0] if single_state and fin else fin)
        except _trace_errors():
            pass  # body not trace-safe: run the eager unrolled loop

    T = data_list[0].shape[0]
    outputs = []
    for t in range(T):
        sliced = [d[t] for d in data_list]
        out, states = body(sliced[0] if len(sliced) == 1 else sliced,
                           states[0] if single_state else states)
        states = _as_list(states)
        outputs.append(out)
    if outputs and isinstance(outputs[0], (list, tuple)):
        merged = [nd_stack(*[o[i] for o in outputs], axis=0)
                  for i in range(len(outputs[0]))]
    else:
        merged = nd_stack(*outputs, axis=0)
    return merged, (states[0] if single_state and states else states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Reference contrib.py while_loop (_while_loop op :1150): iterate
    `func` while `cond` holds, up to max_iterations; step outputs are
    stacked and zero-padded to max_iterations like the reference."""
    import jax.numpy as jnp
    from . import stack as nd_stack, zeros

    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    loop_vars = _as_list(loop_vars)

    if not autograd.is_recording():
        from ..ops.registry import invoke
        try:
            res = invoke("_while_loop", *loop_vars, cond=cond, func=func,
                         max_iterations=int(max_iterations))
            n_vars = len(loop_vars)
            steps_arr, outs, fin = res[0], res[1:len(res) - n_vars], \
                res[len(res) - n_vars:]
            if int(steps_arr.asnumpy()) == 0:
                raise MXNetError("while_loop made no iterations; cond was false")
            return (outs[0] if len(outs) == 1 else outs), fin
        except _trace_errors():
            pass  # cond/func not trace-safe: eager loop below

    outputs = []
    steps = 0
    while steps < max_iterations and bool(cond(*loop_vars).asnumpy()):
        out, loop_vars = func(*loop_vars)
        loop_vars = _as_list(loop_vars)
        if out is not None:
            outputs.append(_as_list(out))
        steps += 1
    if steps == 0:
        raise MXNetError("while_loop made no iterations; cond was false")
    if not outputs:
        return [], loop_vars
    stacked = []
    for i in range(len(outputs[0])):
        arr = nd_stack(*[o[i] for o in outputs], axis=0)
        if steps < max_iterations:
            pad = zeros((max_iterations - steps,) + arr.shape[1:],
                        dtype=arr.dtype)
            from . import concatenate
            arr = concatenate([arr, pad], axis=0)
        stacked.append(arr)
    return (stacked[0] if len(stacked) == 1 else stacked), loop_vars


def cond(pred, then_func, else_func):
    """Reference contrib.py cond (_cond op): evaluate one branch."""
    p = pred() if callable(pred) else pred
    flag = bool(p.asnumpy()) if isinstance(p, NDArray) else bool(p)
    return then_func() if flag else else_func()


def __getattr__(name):
    # contrib-prefixed ops resolve from the registry (nd.contrib.box_nms...)
    from . import _make_wrapper
    from ..ops import registry as _registry

    for candidate in (f"_contrib_{name}", name):
        if candidate in _registry.OPS:
            w = _make_wrapper(_registry.OPS.get(candidate))
            globals()[name] = w  # cache: next access skips __getattr__
            return w
    raise AttributeError(f"nd.contrib has no attribute {name!r}")
