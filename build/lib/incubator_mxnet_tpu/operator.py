"""Custom operator framework: user-defined Python ops with autograd.

Reference: python/mxnet/operator.py (1,160 LoC — CustomOp/CustomOpProp/
register) + src/operator/custom/ (the CustomOperator singleton runs Python
callbacks on its own worker thread so the GIL never blocks engine workers,
custom-inl.h:52).

TPU-native redesign: there is no engine thread to protect — eager dispatch
is already host-side Python, so a custom op runs inline. The tape hook is
the same one every registry op uses (autograd.Node), so custom backward
composes with the rest of the graph. Custom ops are host-side by nature
(arbitrary Python); inside a jit trace they are rejected with a clear
error, mirroring the reference's constraint that custom ops break graph
fusion boundaries.
"""
from __future__ import annotations

import weakref

from .base import MXNetError, Registry

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_CUSTOM = Registry("custom_op")


class CustomOp:
    """Base for user ops (reference operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write src into dst honoring the OpReqType (reference
        operator.py CustomOp.assign)."""
        if req == "null":
            return
        src_data = src._data if hasattr(src, "_data") else src
        if req in ("write", "inplace"):
            dst._data = src_data
        elif req == "add":
            dst._data = dst._data + src_data
        else:
            raise MXNetError(f"invalid req {req!r}")


class CustomOpProp:
    """Op metadata + factory (reference operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Decorator: @operator.register("my_op") on a CustomOpProp subclass
    (reference operator.py register)."""

    def _do(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register() expects a CustomOpProp subclass")
        _CUSTOM.register(prop_cls, name=reg_name)
        return prop_cls

    return _do


def get_all_registered():
    return _CUSTOM.keys()


def invoke_custom(*data, op_type, **kwargs):
    """`nd.Custom(*data, op_type=...)` entry (reference: the `Custom` op,
    src/operator/custom/custom.cc)."""
    import jax

    from . import autograd
    from .ndarray import NDArray, zeros

    if any(isinstance(getattr(a, "_data", a), jax.core.Tracer) for a in data):
        raise MXNetError(
            "custom ops run host-side Python and cannot be traced into a "
            "compiled graph; call them eagerly (reference custom ops have "
            "the same fusion-boundary constraint)")
    prop_cls = _CUSTOM.get(op_type)
    prop = prop_cls(**kwargs)
    arg_names = prop.list_arguments()
    if len(data) != len(arg_names):
        raise MXNetError(f"{op_type} expects {len(arg_names)} inputs "
                         f"({arg_names}), got {len(data)}")
    in_shapes = [list(a.shape) for a in data]
    in_shapes, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    in_types, out_types, aux_types = prop.infer_type(
        [a.dtype for a in data])
    op = prop.create_operator(None, in_shapes, in_types)

    in_data = list(data)
    out_data = [zeros(tuple(s), dtype=t)
                for s, t in zip(out_shapes, out_types)]
    aux = [zeros(tuple(s), dtype=t)
           for s, t in zip(aux_shapes, aux_types)]

    op.forward(is_train=autograd.is_training() or autograd.is_recording(),
               req=["write"] * len(out_data), in_data=in_data,
               out_data=out_data, aux=aux)

    if autograd.is_recording():
        saved_out = [NDArray(o._data) for o in out_data]

        def node_vjp(cts):
            cts_t = cts if isinstance(cts, tuple) else (cts,)
            out_grad = [NDArray(c) for c in cts_t]
            in_grad = [zeros(a.shape, dtype=a.dtype) for a in in_data]
            op.backward(req=["write"] * len(in_grad), out_grad=out_grad,
                        in_data=in_data, out_data=saved_out,
                        in_grad=in_grad, aux=aux)
            return tuple(g._data for g in in_grad)

        node = autograd.Node(node_vjp, list(in_data), f"custom_{op_type}")
        node.out_refs = [weakref.ref(o) for o in out_data]
        node.out_avals = [(o.shape, o.dtype) for o in out_data]
        for o in out_data:
            o._ag_node = node

    return out_data[0] if len(out_data) == 1 else out_data
