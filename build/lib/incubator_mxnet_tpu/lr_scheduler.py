"""Learning-rate schedules.

Covers the reference set (python/mxnet/lr_scheduler.py: Factor/MultiFactor/
Poly/Cosine with linear/constant warmup) as PURE functions of the update
count: the base class blends warmup with the subclass's `_decayed(t)`, and
no schedule mutates its own state between calls — the same `num_update`
always yields the same lr, which keeps schedules safe to call from multiple
updaters and trivially checkpointable.
"""
from __future__ import annotations

import bisect
import math

from .base import MXNetError

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    """Callable: lr = scheduler(num_update)."""

    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        if warmup_begin_lr > base_lr:
            raise MXNetError("warmup_begin_lr must be <= base_lr")
        if warmup_mode not in ("linear", "constant"):
            raise MXNetError(f"warmup_mode must be linear or constant, "
                             f"got {warmup_mode!r}")
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        frac = num_update / max(1, self.warmup_steps)
        return self.warmup_begin_lr + \
            (self.warmup_final_lr - self.warmup_begin_lr) * frac

    def _decayed(self, num_update):
        return self.base_lr

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        return self._decayed(num_update)


class FactorScheduler(LRScheduler):
    """lr = base_lr * factor^(t // step), floored at stop_factor_lr."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8, base_lr=0.01,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if step < 1:
            raise MXNetError("step must be >= 1")
        if not 0 < factor <= 1:
            raise MXNetError("factor must be in (0, 1]")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def _decayed(self, num_update):
        # strict boundary: no drop at num_update == k*step itself, matching
        # MultiFactorScheduler's bisect_left milestone semantics below
        drops = max(0, num_update - 1) // self.step
        return max(self.stop_factor_lr, self.base_lr * self.factor ** drops)


class MultiFactorScheduler(LRScheduler):
    """lr drops by `factor` at each milestone in `step` (ascending list)."""

    def __init__(self, step, factor=1, base_lr=0.01, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if not step or list(step) != sorted(step):
            raise MXNetError("step must be a non-empty ascending list")
        if not 0 < factor <= 1:
            raise MXNetError("factor must be in (0, 1]")
        self.step = list(step)
        self.factor = factor

    def _decayed(self, num_update):
        passed = bisect.bisect_left(self.step, num_update)
        return self.base_lr * self.factor ** passed


class _AnnealToFinal(LRScheduler):
    """Shared shape for poly/cosine: interpolate base_lr -> final_lr over
    (max_update - warmup_steps) with a subclass-specific curve."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if max_update <= warmup_steps:
            raise MXNetError("max_update must exceed warmup_steps")
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - warmup_steps

    def _curve(self, frac):
        raise NotImplementedError

    def _decayed(self, num_update):
        if num_update >= self.max_update:
            return self.final_lr
        frac = (num_update - self.warmup_steps) / self.max_steps
        return self.final_lr + (self.base_lr - self.final_lr) * \
            self._curve(frac)


class PolyScheduler(_AnnealToFinal):
    """(1 - frac)^pwr polynomial decay."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(max_update, base_lr, final_lr, warmup_steps,
                         warmup_begin_lr, warmup_mode)
        self.power = pwr

    def _curve(self, frac):
        return (1.0 - frac) ** self.power


class CosineScheduler(_AnnealToFinal):
    """Half-cosine decay."""

    def _curve(self, frac):
        return 0.5 * (1.0 + math.cos(math.pi * frac))
