"""Shared test utilities, shipped in the package so all frontends/CI reuse it.

Reference: python/mxnet/test_utils.py (2,212 LoC): assert_almost_equal:501,
check_numeric_gradient:872, check_symbolic_forward:1015/backward:1097,
check_consistency:1304, rand_ndarray, same:480, default_context().
"""
from __future__ import annotations

import numpy as _np

from . import autograd, nd
from .context import Context, cpu, current_context

__all__ = ["default_context", "assert_almost_equal", "same", "rand_ndarray",
           "rand_shape_2d", "rand_shape_3d", "check_numeric_gradient",
           "check_consistency", "almost_equal"]

_default_ctx = None


def default_context() -> Context:
    return _default_ctx or current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def _dtype_tol(dtype):
    d = _np.dtype(dtype) if "bfloat16" not in str(dtype) else None
    if d is None or d == _np.float16:
        return 1e-2, 1e-2
    if d == _np.float64:
        return 1e-7, 1e-9
    return 1e-4, 1e-5


def same(a, b):
    return _np.array_equal(_to_np(a), _to_np(b))


def _to_np(a):
    return a.asnumpy() if isinstance(a, nd.NDArray) else _np.asarray(a)


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _to_np(a), _to_np(b)
    drt, dat = _dtype_tol(a.dtype)
    return _np.allclose(a, b, rtol=rtol or drt, atol=atol or dat)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    """dtype-aware tolerance compare (reference test_utils.py:501)."""
    a, b = _to_np(a), _to_np(b)
    drt, dat = _dtype_tol(a.dtype)
    _np.testing.assert_allclose(a, b, rtol=rtol if rtol is not None else drt,
                                atol=atol if atol is not None else dat,
                                err_msg=f"{names[0]} != {names[1]}")


def rand_ndarray(shape, dtype="float32", ctx=None, scale=1.0):
    return nd.array(_np.random.uniform(-scale, scale, shape).astype(dtype), ctx=ctx)


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(_np.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(_np.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference gradient check against autograd
    (reference test_utils.py:872 check_numeric_gradient)."""
    arrays = [nd.array(x) if not isinstance(x, nd.NDArray) else x for x in inputs]
    for a in arrays:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrays)
        if isinstance(out, (list, tuple)):
            out = sum((o.sum() for o in out[1:]), out[0].sum())
        elif out.size != 1:
            out = out.sum()
    out.backward()
    analytic = [a.grad.asnumpy().copy() for a in arrays]

    for ai, a in enumerate(arrays):
        base = a.asnumpy().astype(_np.float64)
        num = _np.zeros_like(base)
        flat = base.reshape(-1)
        numf = num.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            with autograd.pause():
                fp = _scalar_eval(fn, arrays, ai, base)
            flat[i] = orig - eps
            with autograd.pause():
                fm = _scalar_eval(fn, arrays, ai, base)
            flat[i] = orig
            numf[i] = (fp - fm) / (2 * eps)
        _np.testing.assert_allclose(analytic[ai], num, rtol=rtol, atol=atol,
                                    err_msg=f"gradient mismatch on input {ai}")


def _scalar_eval(fn, arrays, ai, perturbed):
    saved = arrays[ai]._data
    arrays[ai]._data = nd.array(perturbed.astype(_np.float32))._data
    try:
        out = fn(*arrays)
        if isinstance(out, (list, tuple)):
            return float(sum(float(o.sum().asscalar()) for o in out))
        return float(out.sum().asscalar())
    finally:
        arrays[ai]._data = saved


def check_consistency(fn, inputs, ctx_list=None, dtype_list=None, rtol=None,
                      atol=None, ref_dtype="float32"):
    """Run fn across a (context x dtype) matrix and compare every run
    against the highest-precision one — the reference's cross-device
    oracle (test_utils.py:1304), which validates GPU kernels against CPU
    there and bf16/f16 TPU paths against fp32 here.

    Each entry of the matrix gets dtype-aware tolerances unless rtol/atol
    are forced. Returns {(ctx, dtype): np output}.
    """
    ctx_list = ctx_list or [cpu(0)]
    dtype_list = dtype_list or [ref_dtype]
    results = {}
    for ctx in ctx_list:
        for dt in dtype_list:
            arrs = [nd.array(_np.asarray(x), ctx=ctx).astype(dt)
                    for x in inputs]
            out = fn(*arrs)
            out = out[0] if isinstance(out, (list, tuple)) else out
            results[(str(ctx), str(dt))] = _to_np(out)
    ref_key = next((k for k in results if k[1] == str(ref_dtype)),
                   next(iter(results)))
    ref = results[ref_key].astype(_np.float64)
    for key, o in results.items():
        if key == ref_key:
            continue
        drt, dat = _dtype_tol(o.dtype)
        _np.testing.assert_allclose(
            o.astype(_np.float64), ref,
            rtol=rtol if rtol is not None else drt,
            atol=atol if atol is not None else dat,
            err_msg=f"{key} inconsistent with {ref_key}")
    return results
