"""Flagship model definitions.

- vision CNNs come from gluon.model_zoo (ResNet-50 is the benchmark flagship,
  BASELINE.md headline rows).
- transformer.py is the SPMD language-model used to exercise dp/tp/sp
  parallelism (capability the reference lacks, SURVEY.md §2.3 last row).
"""
from . import transformer
from .transformer import TransformerLM, TransformerConfig

__all__ = ["transformer", "TransformerLM", "TransformerConfig"]
