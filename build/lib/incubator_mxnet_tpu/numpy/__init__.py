"""mxnet.numpy: NumPy-compatible array namespace (reference
python/mxnet/numpy/, 3,559 LoC, backed by src/operator/numpy/).

Usage mirrors the reference:

    from incubator_mxnet_tpu import np, npx
    x = np.ones((2, 3))
    y = np.exp(x).sum(axis=1)
"""
from .multiarray import *  # noqa: F401,F403
from .multiarray import ndarray, array, _as_np  # noqa: F401
from . import linalg  # noqa: F401
from . import random  # noqa: F401
