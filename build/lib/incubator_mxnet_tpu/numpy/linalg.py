"""mxnet.numpy.linalg (reference python/mxnet/numpy/linalg.py; C++ la_op
kernels src/operator/tensor/la_op.cc are replaced by XLA's native
cholesky/qr/svd/triangular-solve lowerings)."""
from __future__ import annotations

from ..ops.registry import apply_op
from .multiarray import _as_np, _op, array

__all__ = ["norm", "svd", "cholesky", "qr", "inv", "pinv", "det", "slogdet",
           "eigh", "eigvalsh", "solve", "lstsq", "matrix_rank",
           "tensorinv", "multi_dot", "matrix_power"]


def _jla():
    import jax.numpy as jnp
    return jnp.linalg


def norm(x, ord=None, axis=None, keepdims=False):  # noqa: A002
    op = _op("linalg_norm", lambda a, ord, axis, keepdims:
             _jla().norm(a, ord=ord, axis=axis, keepdims=keepdims))
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op(op, _as_np(x), ord=ord, axis=ax, keepdims=bool(keepdims))


def svd(a, full_matrices=False, compute_uv=True):
    op = _op("linalg_svd", lambda x, full_matrices, compute_uv:
             _jla().svd(x, full_matrices=full_matrices,
                        compute_uv=compute_uv))
    return apply_op(op, _as_np(a), full_matrices=bool(full_matrices),
                    compute_uv=bool(compute_uv))


def cholesky(a):
    op = _op("linalg_cholesky", lambda x: _jla().cholesky(x))
    return apply_op(op, _as_np(a))


def qr(a, mode="reduced"):
    op = _op("linalg_qr", lambda x, mode: _jla().qr(x, mode=mode))
    return apply_op(op, _as_np(a), mode=mode)


def inv(a):
    op = _op("linalg_inv", lambda x: _jla().inv(x))
    return apply_op(op, _as_np(a))


def pinv(a, rcond=1e-15):
    op = _op("linalg_pinv", lambda x, rcond: _jla().pinv(x, rcond=rcond))
    return apply_op(op, _as_np(a), rcond=float(rcond))


def det(a):
    op = _op("linalg_det", lambda x: _jla().det(x))
    return apply_op(op, _as_np(a))


def slogdet(a):
    op = _op("linalg_slogdet", lambda x: tuple(_jla().slogdet(x)))
    return apply_op(op, _as_np(a))


def eigh(a):
    op = _op("linalg_eigh", lambda x: tuple(_jla().eigh(x)))
    return apply_op(op, _as_np(a))


def eigvalsh(a):
    op = _op("linalg_eigvalsh", lambda x: _jla().eigvalsh(x))
    return apply_op(op, _as_np(a))


def solve(a, b):
    op = _op("linalg_solve", lambda x, y: _jla().solve(x, y))
    return apply_op(op, _as_np(a), _as_np(b))


def lstsq(a, b, rcond=None):
    import jax.numpy as jnp
    res = _jla().lstsq(_as_np(a)._data, _as_np(b)._data, rcond=rcond)
    return tuple(array(r) for r in res)


def matrix_rank(a, tol=None):
    op = _op("linalg_matrix_rank",
             lambda x, tol: _jla().matrix_rank(x, tol=tol), nondiff=True)
    return apply_op(op, _as_np(a), tol=tol)


def tensorinv(a, ind=2):
    op = _op("linalg_tensorinv",
             lambda x, ind: _jla().tensorinv(x, ind=ind))
    return apply_op(op, _as_np(a), ind=int(ind))


def multi_dot(arrays):
    op = _op("linalg_multi_dot", lambda *xs: _jla().multi_dot(xs))
    return apply_op(op, *[_as_np(x) for x in arrays])


def matrix_power(a, n):
    op = _op("linalg_matrix_power",
             lambda x, n: _jla().matrix_power(x, n))
    return apply_op(op, _as_np(a), n=int(n))
