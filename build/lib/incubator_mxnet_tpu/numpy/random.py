"""mxnet.numpy.random (reference python/mxnet/numpy/random.py).

Samplers ride the framework key chain (ndarray/random.py next_key) as
stateful registry ops, so they are reproducible under mx.random.seed and
trace-safe inside hybridized blocks. Distribution parameters are passed as
traced array inputs (scalars coerced to 0-d arrays), so the jit cache is
keyed on shapes only — changing `loc`/`scale` never recompiles."""
from __future__ import annotations

from ..base import dtype_np
from ..ops.registry import OPS, OpDef, apply_op
from .multiarray import _as_np, _np_ops, ndarray

__all__ = ["uniform", "normal", "randn", "rand", "randint", "choice",
           "shuffle", "permutation", "beta", "gamma", "exponential",
           "chisquare", "multinomial", "multivariate_normal", "lognormal",
           "laplace", "gumbel", "logistic", "pareto", "power", "rayleigh",
           "weibull", "seed"]


def _op_stateful(name, fn):
    key = "random_" + name
    op = _np_ops.get(key)
    if op is None:
        op = OpDef("_npi_random_" + name, fn, stateful=True)
        OPS.register(op, name="_npi_random_" + name)
        _np_ops[key] = op
    return op


def _size(size):
    if size is None:
        return None
    return (size,) if isinstance(size, int) else tuple(size)


def seed(s):
    from ..ndarray import random as _r
    _r.seed(s)


def _jr():
    import jax
    return jax.random


def _shape_of(shape, *params):
    """Output shape: explicit `size`, else broadcast of parameter shapes."""
    if shape is not None:
        return shape
    import numpy as _onp
    return _onp.broadcast_shapes(*[p.shape for p in params]) if params else ()


def _two_param(name, sample):
    """Samplers of the form loc/scale (or low/high): out = sample over
    broadcast shape, parameters traced."""

    def func(arg1=0.0, arg2=1.0, size=None, dtype="float32", ctx=None):
        def fn(p1, p2, *, rng, shape, dtype):
            out_shape = _shape_of(shape, p1, p2)
            return sample(rng, p1, p2, out_shape, dtype)

        op = _op_stateful(name, fn)
        return apply_op(op, _as_np(arg1, dtype=dtype), _as_np(arg2, dtype=dtype),
                        shape=_size(size), dtype=dtype_np(dtype))

    func.__name__ = name
    return func


def _one_param(name, sample):
    def func(arg1=1.0, size=None, dtype="float32", ctx=None):
        def fn(p1, *, rng, shape, dtype):
            return sample(rng, p1, _shape_of(shape, p1), dtype)

        op = _op_stateful(name, fn)
        return apply_op(op, _as_np(arg1, dtype=dtype), shape=_size(size),
                        dtype=dtype_np(dtype))

    func.__name__ = name
    return func


def _exp(x):
    import jax.numpy as jnp
    return jnp.exp(x)


uniform = _two_param(
    "uniform", lambda rng, lo, hi, s, dt:
    _jr().uniform(rng, s, dt) * (hi - lo) + lo)
normal = _two_param(
    "normal", lambda rng, loc, sc, s, dt:
    _jr().normal(rng, s, dt) * sc + loc)
laplace = _two_param(
    "laplace", lambda rng, loc, sc, s, dt:
    _jr().laplace(rng, s, dt) * sc + loc)
gumbel = _two_param(
    "gumbel", lambda rng, loc, sc, s, dt:
    _jr().gumbel(rng, s, dt) * sc + loc)
logistic = _two_param(
    "logistic", lambda rng, loc, sc, s, dt:
    _jr().logistic(rng, s, dt) * sc + loc)
lognormal = _two_param(
    "lognormal", lambda rng, mean, sig, s, dt:
    _exp(_jr().normal(rng, s, dt) * sig + mean))
beta = _two_param(
    "beta", lambda rng, a, b, s, dt: _jr().beta(rng, a, b, s, dt))
exponential = _one_param(
    "exponential", lambda rng, sc, s, dt:
    _jr().exponential(rng, s, dt) * sc)
rayleigh = _one_param(
    "rayleigh", lambda rng, sc, s, dt: _jr().rayleigh(rng, s, dt) * sc)
pareto = _one_param(
    "pareto", lambda rng, a, s, dt: _jr().pareto(rng, a, s, dt) - 1.0)
power = _one_param(
    "power", lambda rng, a, s, dt: _jr().uniform(rng, s, dt) ** (1.0 / a))
weibull = _one_param(
    "weibull", lambda rng, a, s, dt:
    (-_log_u(rng, s, dt)) ** (1.0 / a))
chisquare = _one_param(
    "chisquare", lambda rng, df, s, dt: _jr().chisquare(rng, df, s, dt))


def _log_u(rng, s, dt):
    import jax.numpy as jnp
    return jnp.log1p(-_jr().uniform(rng, s, dt))


def gamma(shape, scale=1.0, size=None, dtype="float32", ctx=None):
    def fn(a, sc, *, rng, shape, dtype):
        return _jr().gamma(rng, a, _shape_of(shape, a, sc), dtype) * sc

    op = _op_stateful("gamma", fn)
    return apply_op(op, _as_np(shape, dtype=dtype), _as_np(scale, dtype=dtype),
                    shape=_size(size), dtype=dtype_np(dtype))


def randint(low, high=None, size=None, dtype="int32", ctx=None):
    if high is None:
        low, high = 0, low

    def fn(*, rng, shape, dtype, low, high):
        return _jr().randint(rng, shape or (), low, high, dtype)

    op = _op_stateful("randint", fn)
    return _as_np(apply_op(op, shape=_size(size), dtype=dtype_np(dtype),
                           low=int(low), high=int(high)))


def rand(*size):
    return uniform(0.0, 1.0, size=size or None)


def randn(*size):
    return normal(0.0, 1.0, size=size or None)


def choice(a, size=None, replace=True, p=None):
    if hasattr(a, "_data") or not isinstance(a, int):
        pool = _as_np(a)
        if p is not None:
            def fn(arr, pp, *, rng, shape, replace):
                return _jr().choice(rng, arr, shape or (), replace=replace,
                                    p=pp)
            op = _op_stateful("choice_arr_p", fn)
            return apply_op(op, pool, _as_np(p), shape=_size(size),
                            replace=bool(replace))

        def fn(arr, *, rng, shape, replace):
            return _jr().choice(rng, arr, shape or (), replace=replace)
        op = _op_stateful("choice_arr", fn)
        return apply_op(op, pool, shape=_size(size), replace=bool(replace))

    if p is not None:
        def fn(pp, *, rng, shape, replace, n):
            return _jr().choice(rng, n, shape or (), replace=replace, p=pp)
        op = _op_stateful("choice_n_p", fn)
        return apply_op(op, _as_np(p), shape=_size(size),
                        replace=bool(replace), n=int(a))

    def fn(*, rng, shape, replace, n):
        return _jr().choice(rng, n, shape or (), replace=replace)
    op = _op_stateful("choice_n", fn)
    return _as_np(apply_op(op, shape=_size(size), replace=bool(replace),
                           n=int(a)))


def shuffle(x):
    """In-place permutation along axis 0 (matches reference semantics)."""
    def fn(a, *, rng):
        return _jr().permutation(rng, a, axis=0)

    op = _op_stateful("shuffle", fn)
    out = apply_op(op, _as_np(x))
    x._data = out._data
    return None


def permutation(x):
    if isinstance(x, int):
        def fn(*, rng, n):
            return _jr().permutation(rng, n)
        op = _op_stateful("permutation_n", fn)
        return _as_np(apply_op(op, n=int(x)))

    def fn(a, *, rng):
        return _jr().permutation(rng, a, axis=0)
    op = _op_stateful("permutation", fn)
    return apply_op(op, _as_np(x))


def multinomial(n, pvals, size=None):
    def fn(p, *, rng, shape, n):
        import jax
        return jax.random.multinomial(
            rng, n, p, shape=(shape + p.shape) if shape else None)

    op = _op_stateful("multinomial", fn)
    return apply_op(op, _as_np(pvals), shape=_size(size), n=int(n))


def multivariate_normal(mean, cov, size=None):
    def fn(m, c, *, rng, shape):
        return _jr().multivariate_normal(rng, m, c, shape)

    op = _op_stateful("multivariate_normal", fn)
    return apply_op(op, _as_np(mean), _as_np(cov), shape=_size(size))
