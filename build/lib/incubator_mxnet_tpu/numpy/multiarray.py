"""mxnet.numpy: NumPy-semantics array + function namespace.

Reference: python/mxnet/numpy/multiarray.py (3,088 LoC) — a NumPy-compatible
`ndarray` backed by the `_np_*` operator registrations
(src/operator/numpy/, 3,762 LoC C++), with true scalars (0-d), boolean
indexing, and NumPy broadcasting/naming conventions.

TPU-native redesign: jax.numpy IS a NumPy-semantics tensor library, so each
function here is one OpDef wrapping the jnp function, dispatched through
ops/registry.apply_op — which gives autograd recording, the cached-jit eager
fast path, AMP/profiler hooks, and class preservation (an `np.ndarray` input
produces `np.ndarray` outputs through every registered op) without
duplicating the op surface the way the reference does.
"""
from __future__ import annotations

import builtins

import numpy as _onp

from ..base import MXNetError, dtype_np
from ..ndarray.ndarray import NDArray
from ..ops.registry import OPS, OpDef, apply_op

__all__ = ["ndarray", "array"]  # extended programmatically below


def _jnp():
    import jax.numpy as jnp
    return jnp


class ndarray(NDArray):
    """NumPy-semantics array (reference numpy/multiarray.py `ndarray`).

    Inherits the full NDArray surface; registry ops preserve this class, so
    arithmetic/indexing/reductions all stay in the numpy namespace."""

    __slots__ = ()

    def as_nd_ndarray(self):
        """View as classic nd.NDArray, preserving the autograd tape."""
        return _rewrap(NDArray, self)

    def as_np_ndarray(self):
        return self

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def __matmul__(self, other):
        return matmul(self, _as_np(other))

    def __rmatmul__(self, other):
        return matmul(_as_np(other), self)

    def __floordiv__(self, other):
        return floor_divide(self, other)

    def __rfloordiv__(self, other):
        return floor_divide(_as_np(other), self)

    def __repr__(self):
        try:
            return repr(self.asnumpy())
        except Exception:
            return f"<traced {self.shape} {self.dtype}>"

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return reshape(self, shape)

    def flatten(self):
        return reshape(self, (-1,))

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return transpose(self, axes=axes if axes else None)

    def astype(self, dtype, copy=True):
        from ..base import dtype_name
        op = _op("astype", lambda x, *, dtype: x.astype(dtype))
        return apply_op(op, self, dtype=dtype_name(dtype_np(dtype)))

    def copy(self):
        return _rewrap(ndarray, self)

    # numpy comparisons return bool arrays (the classic nd namespace keeps
    # MXNet's float-0/1 convention, reference multiarray.py __eq__)
    def __eq__(self, other):
        if other is None:
            return False
        return equal(self, other)

    def __ne__(self, other):
        if other is None:
            return True
        return not_equal(self, other)

    def __lt__(self, other):
        return less(self, other)

    def __le__(self, other):
        return less_equal(self, other)

    def __gt__(self, other):
        return greater(self, other)

    def __ge__(self, other):
        return greater_equal(self, other)

    __hash__ = NDArray.__hash__


def _rewrap(cls, arr):
    """Re-class an array without breaking the autograd tape.

    The tape routes cotangents by object identity (autograd.backward
    out_refs), so a recorded intermediate must register the new view as an
    alias of the original output slot or its gradient would be dropped."""
    out = cls.__new__(cls)
    out._data = arr._data
    out._grad = arr._grad
    out._grad_req = arr._grad_req
    out._ag_node = arr._ag_node
    if arr._ag_node is not None:
        arr._ag_node.add_alias(arr, out)
    return out


def _as_np(x, dtype=None):
    if isinstance(x, ndarray):
        return x
    if isinstance(x, NDArray):
        return _rewrap(ndarray, x)
    return ndarray(_jnp().asarray(x, dtype=dtype_np(dtype) if dtype else None))


# ---------------------------------------------------------------------------
# op plumbing: one cached OpDef per numpy function
# ---------------------------------------------------------------------------

_np_ops: dict = {}


def _op(name, fn, nondiff=False):
    op = _np_ops.get(name)
    if op is None:
        op = OpDef("_np_" + name, fn, nondiff=nondiff)
        OPS.register(op, name="_np_" + name)
        _np_ops[name] = op
    return op


def _unary(name, jfn=None, nondiff=False):
    def func(x, out=None, **kwargs):
        jnp = _jnp()
        f = jfn if jfn is not None else getattr(jnp, name)
        op = _op(name, lambda a, **kw: f(a, **kw), nondiff=nondiff)
        return apply_op(op, _as_np(x), out=out, **kwargs)

    func.__name__ = name
    func.__doc__ = f"numpy.{name} semantics over jnp.{name}."
    return func


def _binary(name, jfn=None, nondiff=False):
    def func(x1, x2, out=None, **kwargs):
        jnp = _jnp()
        f = jfn if jfn is not None else getattr(jnp, name)
        op = _op(name, lambda a, b, **kw: f(a, b, **kw), nondiff=nondiff)
        return apply_op(op, _as_np(x1), _as_np(x2), out=out, **kwargs)

    func.__name__ = name
    func.__doc__ = f"numpy.{name} semantics over jnp.{name}."
    return func


def _reduction(name, jfn=None, nondiff=False):
    def func(a, axis=None, dtype=None, keepdims=False, out=None, **kwargs):
        jnp = _jnp()
        f = jfn if jfn is not None else getattr(jnp, name)
        params = dict(kwargs)
        if axis is not None:
            params["axis"] = tuple(axis) if isinstance(axis, list) else axis
        if dtype is not None:
            params["dtype"] = dtype_np(dtype)
        if keepdims:
            params["keepdims"] = True
        op = _op(name, lambda x, **kw: f(x, **kw), nondiff=nondiff)
        return apply_op(op, _as_np(a), out=out, **params)

    func.__name__ = name
    return func


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def array(obj, dtype=None, ctx=None):
    jnp = _jnp()
    if isinstance(obj, NDArray):
        obj = obj._data
    return ndarray(jnp.asarray(obj, dtype=dtype_np(dtype) if dtype else None),
                   ctx=ctx)


def zeros(shape, dtype="float32", ctx=None):
    return ndarray(_jnp().zeros(shape, dtype_np(dtype)), ctx=ctx)


def ones(shape, dtype="float32", ctx=None):
    return ndarray(_jnp().ones(shape, dtype_np(dtype)), ctx=ctx)


def full(shape, fill_value, dtype=None, ctx=None):
    return ndarray(_jnp().full(shape, fill_value,
                               dtype_np(dtype) if dtype else None), ctx=ctx)


def empty(shape, dtype="float32", ctx=None):
    return zeros(shape, dtype=dtype, ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return ndarray(_jnp().arange(start, stop, step,
                                 dtype_np(dtype) if dtype else None), ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    return ndarray(_jnp().linspace(start, stop, num, endpoint=endpoint,
                                   dtype=dtype_np(dtype) if dtype else None),
                   ctx=ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    return ndarray(_jnp().logspace(start, stop, num, endpoint=endpoint,
                                   base=base,
                                   dtype=dtype_np(dtype) if dtype else None),
                   ctx=ctx)


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    return ndarray(_jnp().eye(N, M, k, dtype_np(dtype)), ctx=ctx)


def identity(n, dtype="float32", ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def zeros_like(a, dtype=None):
    op = _op("zeros_like", lambda x, **kw: _jnp().zeros_like(x, **kw),
             nondiff=True)
    return apply_op(op, _as_np(a),
                    **({"dtype": dtype_np(dtype)} if dtype else {}))


def ones_like(a, dtype=None):
    op = _op("ones_like", lambda x, **kw: _jnp().ones_like(x, **kw),
             nondiff=True)
    return apply_op(op, _as_np(a),
                    **({"dtype": dtype_np(dtype)} if dtype else {}))


def full_like(a, fill_value, dtype=None):
    op = _op("full_like",
             lambda x, **kw: _jnp().full_like(x, **kw), nondiff=True)
    return apply_op(op, _as_np(a), fill_value=float(fill_value),
                    **({"dtype": dtype_np(dtype)} if dtype else {}))


def meshgrid(*xi, indexing="xy"):
    op = _op("meshgrid",
             lambda *xs, indexing: _jnp().meshgrid(*xs, indexing=indexing))
    return apply_op(op, *[_as_np(x) for x in xi], indexing=indexing)


def tri(N, M=None, k=0, dtype="float32", ctx=None):
    return ndarray(_jnp().tri(N, M, k, dtype_np(dtype)), ctx=ctx)


# ---------------------------------------------------------------------------
# math: unary / binary / reductions (generated)
# ---------------------------------------------------------------------------

_UNARY_DIFF = [
    "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh", "cosh",
    "tanh", "arcsinh", "arccosh", "arctanh", "exp", "expm1", "log", "log2",
    "log10", "log1p", "sqrt", "cbrt", "square", "reciprocal", "negative",
    "abs", "absolute", "fabs", "sign", "degrees", "radians", "deg2rad",
    "rad2deg", "positive",
]
_UNARY_NONDIFF = [
    "floor", "ceil", "trunc", "rint", "fix", "logical_not", "isnan",
    "isinf", "isfinite", "isposinf", "isneginf", "signbit",
]
_BINARY_DIFF = [
    "add", "subtract", "multiply", "divide", "true_divide", "power",
    "maximum", "minimum", "fmax", "fmin", "arctan2", "hypot", "logaddexp",
    "mod", "remainder", "fmod", "copysign", "float_power",
]
_BINARY_NONDIFF = [
    "floor_divide", "equal", "not_equal", "less", "less_equal", "greater",
    "greater_equal", "logical_and", "logical_or", "logical_xor", "lcm",
    "gcd", "bitwise_and", "bitwise_or", "bitwise_xor", "left_shift",
    "right_shift",
]
_REDUCE_DIFF = ["sum", "mean", "prod", "std", "var", "min", "max", "amin",
                "amax", "cumsum", "cumprod", "nansum", "nanmean", "median"]
_REDUCE_NONDIFF = ["argmin", "argmax", "all", "any", "nanargmin",
                   "nanargmax", "count_nonzero"]

for _n in _UNARY_DIFF:
    globals()[_n] = _unary(_n)
for _n in _UNARY_NONDIFF:
    globals()[_n] = _unary(_n, nondiff=True)
for _n in _BINARY_DIFF:
    globals()[_n] = _binary(_n)
for _n in _BINARY_NONDIFF:
    globals()[_n] = _binary(_n, nondiff=True)
for _n in _REDUCE_DIFF:
    globals()[_n] = _reduction(_n)
for _n in _REDUCE_NONDIFF:
    globals()[_n] = _reduction(_n, nondiff=True)


def invert(x, out=None):
    return _unary("invert", nondiff=True)(x, out=out)


bitwise_not = invert


def round(x, decimals=0, out=None):  # noqa: A001
    op = _op("round", lambda a, decimals: _jnp().round(a, decimals),
             nondiff=True)
    return apply_op(op, _as_np(x), out=out, decimals=int(decimals))


around = round
round_ = round


def clip(a, a_min=None, a_max=None, out=None):
    if isinstance(a_min, NDArray) or isinstance(a_max, NDArray):
        # array bounds become op inputs (broadcastable, differentiable)
        # None bounds pass straight through so integer inputs keep their
        # dtype (an inf array bound would promote the result to float)
        op3 = _op("clip_arr",
                  lambda x, lo=None, hi=None: _jnp().clip(x, lo, hi))
        args3 = [_as_np(a)]
        if a_min is not None:
            args3.append(_as_np(a_min))
            if a_max is not None:
                args3.append(_as_np(a_max))
            return apply_op(op3, *args3, out=out)
        # a_min is None here, and a_max must be set (the enclosing branch
        # requires one array bound)
        op_hi = _op("clip_arr_hi", lambda x, hi: _jnp().clip(x, None, hi))
        return apply_op(op_hi, _as_np(a), _as_np(a_max), out=out)
    # scalar bounds stay static params; keep the input dtype like numpy
    op = _op("clip", lambda x, a_min, a_max:
             _jnp().clip(x,
                         None if a_min is None else _jnp().asarray(a_min, x.dtype),
                         None if a_max is None else _jnp().asarray(a_max, x.dtype)))
    return apply_op(op, _as_np(a), out=out,
                    a_min=None if a_min is None else float(a_min),
                    a_max=None if a_max is None else float(a_max))


def average(a, axis=None, weights=None):
    if weights is None:
        return mean(a, axis=axis)
    op = _op("average",
             lambda x, w, axis: _jnp().average(x, axis=axis, weights=w))
    return apply_op(op, _as_np(a), _as_np(weights),
                    axis=axis if axis is None or isinstance(axis, int)
                    else tuple(axis))


def ptp(a, axis=None, keepdims=False):
    return subtract(max(a, axis=axis, keepdims=keepdims),
                    min(a, axis=axis, keepdims=keepdims))


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------

def reshape(a, newshape, order="C"):
    op = _op("reshape", lambda x, shape: _jnp().reshape(x, shape))
    shape = tuple(newshape) if isinstance(newshape, (list, tuple)) \
        else (newshape,)
    return apply_op(op, _as_np(a), shape=shape)


def transpose(a, axes=None):
    op = _op("transpose", lambda x, axes: _jnp().transpose(x, axes))
    return apply_op(op, _as_np(a),
                    axes=None if axes is None else tuple(axes))


def swapaxes(a, axis1, axis2):
    op = _op("swapaxes",
             lambda x, axis1, axis2: _jnp().swapaxes(x, axis1, axis2))
    return apply_op(op, _as_np(a), axis1=int(axis1), axis2=int(axis2))


def moveaxis(a, source, destination):
    op = _op("moveaxis", lambda x, source, destination:
             _jnp().moveaxis(x, source, destination))
    t = lambda v: tuple(v) if isinstance(v, (list, tuple)) else int(v)
    return apply_op(op, _as_np(a), source=t(source), destination=t(destination))


def expand_dims(a, axis):
    op = _op("expand_dims", lambda x, axis: _jnp().expand_dims(x, axis))
    return apply_op(op, _as_np(a), axis=int(axis))


def squeeze(a, axis=None):
    op = _op("squeeze", lambda x, axis: _jnp().squeeze(x, axis))
    return apply_op(op, _as_np(a),
                    axis=None if axis is None else axis)


def broadcast_to(a, shape):
    op = _op("broadcast_to", lambda x, shape: _jnp().broadcast_to(x, shape))
    return apply_op(op, _as_np(a), shape=tuple(shape))


def ravel(a, order="C"):
    return reshape(a, (-1,))


def concatenate(seq, axis=0, out=None):
    op = _op("concatenate",
             lambda *xs, axis: _jnp().concatenate(xs, axis=axis))
    return apply_op(op, *[_as_np(x) for x in seq], out=out,
                    axis=None if axis is None else int(axis))


def stack(arrays, axis=0, out=None):
    op = _op("stack", lambda *xs, axis: _jnp().stack(xs, axis=axis))
    return apply_op(op, *[_as_np(x) for x in arrays], out=out, axis=int(axis))


def vstack(tup):
    op = _op("vstack", lambda *xs: _jnp().vstack(xs))
    return apply_op(op, *[_as_np(x) for x in tup])


def hstack(tup):
    op = _op("hstack", lambda *xs: _jnp().hstack(xs))
    return apply_op(op, *[_as_np(x) for x in tup])


def dstack(tup):
    op = _op("dstack", lambda *xs: _jnp().dstack(xs))
    return apply_op(op, *[_as_np(x) for x in tup])


def column_stack(tup):
    op = _op("column_stack", lambda *xs: _jnp().column_stack(xs))
    return apply_op(op, *[_as_np(x) for x in tup])


def split(ary, indices_or_sections, axis=0):
    sec = indices_or_sections
    sec = tuple(sec) if isinstance(sec, (list, tuple)) else int(sec)
    op = _op("split", lambda x, sec, axis: _jnp().split(x, sec, axis))
    return apply_op(op, _as_np(ary), sec=sec, axis=int(axis))


def array_split(ary, indices_or_sections, axis=0):
    sec = indices_or_sections
    sec = tuple(sec) if isinstance(sec, (list, tuple)) else int(sec)
    op = _op("array_split",
             lambda x, sec, axis: _jnp().array_split(x, sec, axis))
    return apply_op(op, _as_np(ary), sec=sec, axis=int(axis))


def hsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=1)


def vsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=0)


def flip(m, axis=None):
    op = _op("flip", lambda x, axis: _jnp().flip(x, axis))
    return apply_op(op, _as_np(m),
                    axis=None if axis is None else axis)


def flipud(m):
    return flip(m, 0)


def fliplr(m):
    return flip(m, 1)


def roll(a, shift, axis=None):
    t = lambda v: tuple(v) if isinstance(v, (list, tuple)) else v
    op = _op("roll", lambda x, shift, axis: _jnp().roll(x, shift, axis))
    return apply_op(op, _as_np(a), shift=t(shift), axis=t(axis))


def rot90(m, k=1, axes=(0, 1)):
    op = _op("rot90", lambda x, k, axes: _jnp().rot90(x, k, axes))
    return apply_op(op, _as_np(m), k=int(k), axes=tuple(axes))


def tile(A, reps):
    op = _op("tile", lambda x, reps: _jnp().tile(x, reps))
    return apply_op(op, _as_np(A),
                    reps=tuple(reps) if isinstance(reps, (list, tuple))
                    else int(reps))


def repeat(a, repeats, axis=None):
    op = _op("repeat", lambda x, repeats, axis: _jnp().repeat(x, repeats, axis))
    reps = tuple(int(r) for r in repeats) \
        if isinstance(repeats, (list, tuple, _onp.ndarray)) else int(repeats)
    return apply_op(op, _as_np(a), repeats=reps,
                    axis=None if axis is None else int(axis))


def pad(array_, pad_width, mode="constant", **kwargs):
    def _fn(x, pad_width, mode, kw):
        return _jnp().pad(x, pad_width, mode=mode, **dict(kw))
    op = _op("pad", _fn)
    pw = tuple(tuple(p) if isinstance(p, (list, tuple)) else p
               for p in pad_width) if isinstance(pad_width, (list, tuple)) \
        else pad_width
    return apply_op(op, _as_np(array_), pad_width=pw, mode=mode,
                    kw=tuple(sorted(kwargs.items())))


def atleast_1d(*arys):
    res = [reshape(a, (1,)) if _as_np(a).ndim == 0 else _as_np(a)
           for a in arys]
    return res[0] if len(res) == 1 else res


def atleast_2d(*arys):
    op = _op("atleast_2d", lambda x: _jnp().atleast_2d(x))
    res = [apply_op(op, _as_np(a)) for a in arys]
    return res[0] if len(res) == 1 else res


def atleast_3d(*arys):
    op = _op("atleast_3d", lambda x: _jnp().atleast_3d(x))
    res = [apply_op(op, _as_np(a)) for a in arys]
    return res[0] if len(res) == 1 else res


# ---------------------------------------------------------------------------
# linear algebra / products
# ---------------------------------------------------------------------------

def dot(a, b, out=None):
    op = _op("dot", lambda x, y: _jnp().dot(x, y))
    return apply_op(op, _as_np(a), _as_np(b), out=out)


def matmul(a, b, out=None):
    op = _op("matmul", lambda x, y: _jnp().matmul(x, y))
    return apply_op(op, _as_np(a), _as_np(b), out=out)


def inner(a, b):
    op = _op("inner", lambda x, y: _jnp().inner(x, y))
    return apply_op(op, _as_np(a), _as_np(b))


def outer(a, b):
    op = _op("outer", lambda x, y: _jnp().outer(x, y))
    return apply_op(op, _as_np(a), _as_np(b))


def vdot(a, b):
    op = _op("vdot", lambda x, y: _jnp().vdot(x, y))
    return apply_op(op, _as_np(a), _as_np(b))


def cross(a, b, axis=-1):
    op = _op("cross", lambda x, y, axis: _jnp().cross(x, y, axis=axis))
    return apply_op(op, _as_np(a), _as_np(b), axis=int(axis))


def kron(a, b):
    op = _op("kron", lambda x, y: _jnp().kron(x, y))
    return apply_op(op, _as_np(a), _as_np(b))


def tensordot(a, b, axes=2):
    ax = tuple(tuple(x) if isinstance(x, (list, tuple)) else x for x in axes) \
        if isinstance(axes, (list, tuple)) else int(axes)
    op = _op("tensordot", lambda x, y, axes: _jnp().tensordot(x, y, axes))
    return apply_op(op, _as_np(a), _as_np(b), axes=ax)


def einsum(subscripts, *operands):
    op = _op("einsum",
             lambda *xs, subscripts: _jnp().einsum(subscripts, *xs))
    return apply_op(op, *[_as_np(x) for x in operands], subscripts=subscripts)


def trace(a, offset=0, axis1=0, axis2=1):
    op = _op("trace", lambda x, offset, axis1, axis2:
             _jnp().trace(x, offset, axis1, axis2))
    return apply_op(op, _as_np(a), offset=int(offset), axis1=int(axis1),
                    axis2=int(axis2))


def diag(v, k=0):
    op = _op("diag", lambda x, k: _jnp().diag(x, k))
    return apply_op(op, _as_np(v), k=int(k))


def diagonal(a, offset=0, axis1=0, axis2=1):
    op = _op("diagonal", lambda x, offset, axis1, axis2:
             _jnp().diagonal(x, offset, axis1, axis2))
    return apply_op(op, _as_np(a), offset=int(offset), axis1=int(axis1),
                    axis2=int(axis2))


def tril(m, k=0):
    op = _op("tril", lambda x, k: _jnp().tril(x, k))
    return apply_op(op, _as_np(m), k=int(k))


def triu(m, k=0):
    op = _op("triu", lambda x, k: _jnp().triu(x, k))
    return apply_op(op, _as_np(m), k=int(k))


# ---------------------------------------------------------------------------
# indexing / selection / sorting
# ---------------------------------------------------------------------------

def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    op = _op("where", lambda c, a, b: _jnp().where(c, a, b))
    return apply_op(op, _as_np(condition), _as_np(x), _as_np(y))


def take(a, indices, axis=None, mode="clip"):
    op = _op("take", lambda x, idx, axis, mode:
             _jnp().take(x, idx.astype("int32"), axis=axis, mode=mode))
    return apply_op(op, _as_np(a), _as_np(indices),
                    axis=None if axis is None else int(axis), mode=mode)


def take_along_axis(arr, indices, axis):
    op = _op("take_along_axis", lambda x, idx, axis:
             _jnp().take_along_axis(x, idx.astype("int32"), axis=axis))
    return apply_op(op, _as_np(arr), _as_np(indices), axis=int(axis))


def sort(a, axis=-1):
    op = _op("sort", lambda x, axis: _jnp().sort(x, axis=axis))
    return apply_op(op, _as_np(a), axis=None if axis is None else int(axis))


def argsort(a, axis=-1):
    op = _op("argsort", lambda x, axis: _jnp().argsort(x, axis=axis),
             nondiff=True)
    return apply_op(op, _as_np(a), axis=None if axis is None else int(axis))


def searchsorted(a, v, side="left"):
    op = _op("searchsorted", lambda x, vv, side:
             _jnp().searchsorted(x, vv, side=side), nondiff=True)
    return apply_op(op, _as_np(a), _as_np(v), side=side)


def nonzero(a):
    """Data-dependent output shape: eager-only (concretizes)."""
    res = _onp.nonzero(_as_np(a).asnumpy())
    return tuple(array(r, dtype="int64") for r in res)


def flatnonzero(a):
    return nonzero(ravel(a))[0]


def unique(ar, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    """Data-dependent output shape: eager-only (concretizes)."""
    res = _onp.unique(_as_np(ar).asnumpy(), return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(array(r) for r in res)
    return array(res)


def one_hot(indices, depth, dtype="float32"):
    import jax
    op = _op("one_hot", lambda idx, depth, dtype:
             jax.nn.one_hot(idx.astype("int32"), depth, dtype=dtype),
             nondiff=True)
    return apply_op(op, _as_np(indices), depth=int(depth),
                    dtype=dtype_np(dtype))


def histogram(a, bins=10, range=None):  # noqa: A002
    jnp = _jnp()
    h, e = jnp.histogram(_as_np(a)._data, bins=bins, range=range)
    return array(h), array(e)


def bincount(x, weights=None, minlength=0):
    op = _op("bincount", lambda a, minlength:
             _jnp().bincount(a.astype("int32"), length=None,
                             minlength=minlength), nondiff=True)
    if weights is not None:
        jnp = _jnp()
        return array(jnp.bincount(_as_np(x)._data.astype("int32"),
                                  weights=_as_np(weights)._data,
                                  minlength=minlength))
    return apply_op(op, _as_np(x), minlength=int(minlength))


def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    op = _op("isclose", lambda x, y, rtol, atol, equal_nan:
             _jnp().isclose(x, y, rtol, atol, equal_nan), nondiff=True)
    return apply_op(op, _as_np(a), _as_np(b), rtol=float(rtol),
                    atol=float(atol), equal_nan=bool(equal_nan))


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return builtins.bool(
        _onp.allclose(_as_np(a).asnumpy(), _as_np(b).asnumpy(),
                      rtol=rtol, atol=atol, equal_nan=equal_nan))


def array_equal(a1, a2):
    return builtins.bool(_onp.array_equal(_as_np(a1).asnumpy(),
                                          _as_np(a2).asnumpy()))


def interp(x, xp, fp):
    op = _op("interp", lambda a, b, c: _jnp().interp(a, b, c))
    return apply_op(op, _as_np(x), _as_np(xp), _as_np(fp))


def diff(a, n=1, axis=-1):
    op = _op("diff", lambda x, n, axis: _jnp().diff(x, n=n, axis=axis))
    return apply_op(op, _as_np(a), n=int(n), axis=int(axis))


def gradient(f, *varargs, axis=None):
    jnp = _jnp()
    res = jnp.gradient(_as_np(f)._data, *varargs,
                       **({} if axis is None else {"axis": axis}))
    if isinstance(res, list):
        return [array(r) for r in res]
    return array(res)


def maximum_sctype(t):
    return _onp.float64


def may_share_memory(a, b):
    return False  # jax buffers are immutable; writes never alias


def shares_memory(a, b):
    return False


# ---------------------------------------------------------------------------
# misc API surface
# ---------------------------------------------------------------------------

def shape(a):
    return _as_np(a).shape


def ndim(a):
    return _as_np(a).ndim


def size(a, axis=None):
    if axis is None:
        return _as_np(a).size
    return _as_np(a).shape[axis]


def copy(a):
    return _as_np(a).copy()


def asarray(a, dtype=None):
    return _as_np(a, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return _as_np(a, dtype=dtype)


# dtype aliases + constants re-exported for mx.np.float32-style use
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
dtype = _onp.dtype

_GENERATED = (_UNARY_DIFF + _UNARY_NONDIFF + _BINARY_DIFF + _BINARY_NONDIFF +
              _REDUCE_DIFF + _REDUCE_NONDIFF)
__all__ += _GENERATED + [
    "zeros", "ones", "full", "empty", "arange", "linspace", "logspace",
    "eye", "identity", "zeros_like", "ones_like", "full_like", "meshgrid",
    "tri", "invert", "bitwise_not", "round", "around", "round_", "clip",
    "average", "ptp", "reshape", "transpose", "swapaxes", "moveaxis",
    "expand_dims", "squeeze", "broadcast_to", "ravel", "concatenate",
    "stack", "vstack", "hstack", "dstack", "column_stack", "split",
    "array_split", "hsplit", "vsplit", "flip", "flipud", "fliplr", "roll",
    "rot90", "tile", "repeat", "pad", "atleast_1d", "atleast_2d",
    "atleast_3d", "dot", "matmul", "inner", "outer", "vdot", "cross",
    "kron", "tensordot", "einsum", "trace", "diag", "diagonal", "tril",
    "triu", "where", "take", "take_along_axis", "sort", "argsort",
    "searchsorted", "nonzero", "flatnonzero", "unique", "one_hot",
    "histogram", "bincount", "isclose", "allclose", "array_equal", "interp",
    "diff", "gradient", "shape", "ndim", "size", "copy", "asarray",
    "ascontiguousarray", "float16", "float32", "float64", "int8", "int16",
    "int32", "int64", "uint8", "bool_", "pi", "e", "inf", "nan", "newaxis",
    "dtype",
]
