"""Checkpoint helpers + legacy FeedForward model API.

Reference: python/mxnet/model.py — `save_checkpoint:394` writes
`prefix-symbol.json` + `prefix-####.params` (arg:/aux:-prefixed NDArray map),
`load_checkpoint:426`, `BatchEndParam`, and the legacy `FeedForward:812`
class (thin shim over Module here, as in late-1.x reference usage).
"""
from __future__ import annotations

import logging as _logging
from collections import namedtuple

from . import nd
from .base import MXNetError

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "FeedForward"]

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Write prefix-symbol.json + prefix-{epoch:04d}.params
    (reference model.py:394)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_checkpoint(prefix, epoch):
    """Returns (symbol, arg_params, aux_params) (reference model.py:426)."""
    from . import symbol as sym
    import os

    symbol = None
    if os.path.exists(f"{prefix}-symbol.json"):
        symbol = sym.load(f"{prefix}-symbol.json")
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy training API (reference model.py:812) as a Module shim."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.numpy_batch_size = numpy_batch_size
        self.kwargs = kwargs
        self._mod = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .io import NDArrayIter
        from .module import Module

        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                            shuffle=True)
        mod = Module(self.symbol,
                     data_names=[d.name for d in X.provide_data],
                     label_names=[d.name for d in (X.provide_label or [])],
                     logger=logger or _logging)
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs or None,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch, monitor=monitor,
                num_epoch=self.num_epoch or 1)
        self._mod = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None):
        if self._mod is None:
            raise MXNetError("call fit() before predict()")
        return self._mod.predict(X, num_batch=num_batch)

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None else
                        (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
