from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter, MXDataIter, ImageRecordIter,
                 MNISTIter, LibSVMIter)

__all__ = ["DataBatch", "DataDesc", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MXDataIter", "ImageRecordIter",
           "MNISTIter", "LibSVMIter"]
