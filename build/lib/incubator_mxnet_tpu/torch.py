"""PyTorch interop bridge.

Reference: python/mxnet/torch.py (183 LoC) — a legacy bridge that ran
(Lua)Torch ops on MXNet NDArrays through a C plugin. TPU-native redesign:
the bridge is the DLPack protocol (ndarray/utils.py from_dlpack/
to_dlpack_*): tensors move zero-copy on CPU, and any torch callable can be
applied to NDArrays with `torch_function`. There is no C plugin — torch is
an optional peer framework, imported lazily so the package works without it.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray.utils import from_dlpack

__all__ = ["to_torch", "from_torch", "torch_function"]


def _torch():
    try:
        import torch  # absolute: the real pytorch, not this module
    except ImportError as e:  # pragma: no cover
        raise MXNetError("pytorch is not installed") from e
    return torch


def to_torch(arr):
    """NDArray -> torch.Tensor (zero-copy via dlpack when on CPU; device
    arrays are staged through host memory)."""
    torch = _torch()
    try:
        return torch.from_dlpack(arr._data)
    except Exception:
        return torch.from_numpy(arr.asnumpy())


def from_torch(tensor):
    """torch.Tensor -> NDArray (dlpack, falling back to a host copy for
    non-contiguous / unsupported layouts)."""
    try:
        return from_dlpack(tensor.contiguous())
    except Exception:
        return NDArray(tensor.detach().cpu().numpy())


def torch_function(fn):
    """Wrap a torch callable so it consumes/produces NDArrays:

        l2 = mx.torch.torch_function(lambda a, b: torch.nn.functional
                                     .mse_loss(a, b))
        out = l2(x_nd, y_nd)
    """
    def wrapped(*args, **kwargs):
        conv = [to_torch(a) if isinstance(a, NDArray) else a for a in args]
        kw = {k: to_torch(v) if isinstance(v, NDArray) else v
              for k, v in kwargs.items()}
        out = fn(*conv, **kw)
        torch = _torch()
        if isinstance(out, torch.Tensor):
            return from_torch(out)
        if isinstance(out, (list, tuple)):
            return type(out)(from_torch(o) if isinstance(o, torch.Tensor)
                             else o for o in out)
        return out

    return wrapped
