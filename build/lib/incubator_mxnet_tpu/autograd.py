"""Autograd: record/pause/backward over an eager tape.

Reference: src/imperative/imperative.cc (`RecordOp:193`, `Backward:280`,
thread-local recording flags :27-31) and python/mxnet/autograd.py
(`record():122`, `pause():146`, `train_mode():166`, `mark_variables():197`,
`backward():246`, `grad():273`).

TPU-native redesign: the reference builds an NNVM node tape and replays
`_backward_*` operators through the dependency engine. Here each recorded op
already produced a `jax.vjp` closure at forward time (residuals live on
device), so backward is a reverse-topological walk calling those closures —
XLA is the "engine"; ordering falls out of jax.Array data dependencies.
Higher-order gradients work by re-entering record mode around vjp calls.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "mark_variables",
           "backward", "grad", "Function"]


class _TLS(threading.local):
    def __init__(self):
        super().__init__()
        self.recording = False
        self.training = False


_tls = _TLS()


def is_recording() -> bool:
    return _tls.recording


def is_training() -> bool:
    return _tls.training


def set_recording(flag: bool) -> bool:
    prev, _tls.recording = _tls.recording, bool(flag)
    return prev


def set_training(flag: bool) -> bool:
    prev, _tls.training = _tls.training, bool(flag)
    return prev


class _RecordScope:
    def __init__(self, recording, training):
        self._rec, self._train = recording, training

    def __enter__(self):
        self._prev_rec = _tls.recording if self._rec is not None else None
        self._prev_train = _tls.training if self._train is not None else None
        if self._rec is not None:
            _tls.recording = self._rec
        if self._train is not None:
            _tls.training = self._train
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            _tls.recording = self._prev_rec
        if self._train is not None:
            _tls.training = self._prev_train


def record(train_mode: bool = True):
    """`with autograd.record():` — reference python/mxnet/autograd.py:122."""
    return _RecordScope(True, train_mode)


def pause(train_mode: bool = False):
    """Reference python/mxnet/autograd.py:146."""
    return _RecordScope(False, train_mode)


def train_mode():
    return _RecordScope(None, True)


def predict_mode():
    return _RecordScope(None, False)


class Node:
    """One recorded op application (reference: AGInfo attached to NDArrays,
    src/imperative/imperative.cc RecordOp)."""

    __slots__ = ("vjp_fn", "inputs", "out_refs", "out_avals", "out_aliases",
                 "name", "bwd_info", "replay")

    def __init__(self, vjp_fn, inputs, name=""):
        self.vjp_fn = vjp_fn     # cotangents-tuple -> input-cotangents tuple
        self.inputs = inputs     # list of NDArray
        self.name = name
        self.out_refs = None     # list of weakrefs to output NDArrays
        self.out_avals = None    # list of (shape, dtype) for dead outputs
        self.out_aliases = None  # slot -> extra weakrefs (rewrapped views)
        # (op, params, saved_args, ndarray_positions) for replaying this
        # node's backward as a recorded op (create_graph higher-order path)
        self.bwd_info = None
        # alternative replay hook for composite nodes (hybridized cached
        # blocks): callable cts -> recorded input cotangents
        self.replay = None

    def add_alias(self, orig, view):
        """Register `view` as another identity of output `orig` so backward
        routes cotangents arriving via either object (as_np_ndarray/
        as_nd_ndarray re-class arrays without copying)."""
        import weakref
        if not self.out_refs:
            return
        for i, ref in enumerate(self.out_refs):
            if ref() is orig:
                if self.out_aliases is None:
                    self.out_aliases = {}
                self.out_aliases.setdefault(i, []).append(weakref.ref(view))
                return


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference python/mxnet/autograd.py:197."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._ag_node = None


def _collect_tape(heads):
    """Reverse-topological order of Nodes reachable from head arrays."""
    order, seen = [], set()

    def visit(node):
        if node is None or id(node) in seen:
            return
        seen.add(id(node))
        for inp in node.inputs:
            visit(getattr(inp, "_ag_node", None))
        order.append(node)

    for h in heads:
        visit(getattr(h, "_ag_node", None))
    return order[::-1]


_BWD_OPDEFS = {}


def _record_bwd(node, cts):
    """Replay `node`'s backward as a RECORDED op so the produced input
    cotangents are themselves differentiable (create_graph=True). The
    replayed op recomputes the node's forward under jax.vjp, taking the
    cotangents AND the original input NDArrays as positional arguments —
    second derivatives flow through both."""
    import jax
    import jax.numpy as jnp
    from .ndarray import NDArray
    from .ops import registry as _R

    op, params, saved, nd_pos = node.bwd_info
    ncts = len(cts)
    nd_pos_t = tuple(nd_pos)

    def bwd_replay(*args, _op=op, _p=params):
        cts_ = args[:ncts]
        primals = args[ncts:]
        if _op.stateful:
            def fwd(rng, *xs):
                return _op.fn(*xs, rng=rng, **_p)
        else:
            def fwd(*xs):
                return _op.fn(*xs, **_p)
        out, vjp = jax.vjp(fwd, *primals)
        ct = tuple(_R._match_ct_dtypes(cts_, out)) \
            if isinstance(out, (tuple, list)) else \
            _R._match_ct_dtypes(cts_[0], out)
        gin = vjp(ct)
        sel = tuple(gin[i] for i in nd_pos_t)
        # single cotangent returns bare (everywhere else a 1-tuple output
        # and a single output use different cotangent conventions)
        return sel[0] if len(sel) == 1 else sel

    key = (id(op), _R._hashable(params), ncts, nd_pos_t)
    bdef = _BWD_OPDEFS.get(key)
    if bdef is None:
        bdef = _R.OpDef(f"_backward_{op.name}", bwd_replay)
        if len(_BWD_OPDEFS) > 256:
            _BWD_OPDEFS.pop(next(iter(_BWD_OPDEFS)))
        _BWD_OPDEFS[key] = bdef
    args = [NDArray(c) if not isinstance(c, NDArray) else c for c in cts]
    # primal slots: live NDArray inputs where available (tape-linked),
    # the saved raw value otherwise (rng keys, non-diff args)
    prim = list(saved)
    for j, p in enumerate(nd_pos):
        prim[p] = node.inputs[j]
    with record():
        outs = _R.apply_op(bdef, *args, *prim)
    # bwd_replay returns cotangents already ordered like node.inputs
    return outs if isinstance(outs, list) else [outs]


def backward(heads, head_grads=None, retain_graph=False, train_mode=True,
             create_graph=False):
    """Compute gradients of heads w.r.t. marked variables.

    Reference python/mxnet/autograd.py:246 -> Imperative::Backward
    (src/imperative/imperative.cc:280). Gradients accumulate per the variable's
    grad_req ('write' overwrites, 'add' accumulates, 'null' skips) — the
    reference's OpReqType semantics (include/mxnet/op_attr_types.h:46-60).

    With create_graph=True each node's backward is replayed as a recorded
    op (_record_bwd), so the produced gradients carry their own tape and
    can be differentiated again (reference higher-order autograd).
    """
    import jax.numpy as jnp
    from .ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # cotangent accumulator keyed by id(NDArray); in create_graph mode the
    # accumulated values are NDArrays (recorded adds), else raw jax arrays
    cot: dict[int, object] = {}
    keep = {}
    for h, hg in zip(heads, head_grads):
        if create_graph:
            g = hg if hg is not None else NDArray(jnp.ones(h.shape, h.dtype))
        else:
            g = hg._data if hg is not None else jnp.ones(h.shape, h.dtype)
        _accum(cot, keep, h, g)

    order = _collect_tape(heads)
    if not order and all(getattr(h, "_ag_node", None) is None for h in heads):
        if not any(getattr(h, "_grad", None) is not None for h in heads):
            raise MXNetError("backward() called on arrays with no recorded graph")

    # create_graph must record the ENTIRE backward walk — including
    # cotangent fan-in adds and grad_req='add' accumulation — regardless
    # of whether the caller is inside a record() scope
    scope = record() if create_graph else _RecordScope(None, None)
    with scope:
        _backward_walk(order, cot, keep, create_graph)

    # write into .grad buffers per grad_req
    from .ndarray.sparse import RowSparseNDArray, row_sparse_combine
    from .ndarray import NDArray as _ND
    for arr_id, (arr, g) in keep.items():
        req = getattr(arr, "_grad_req", None)
        if req in (None, "null"):
            continue
        if arr._grad is None:
            continue
        buf_sparse = isinstance(arr._grad, RowSparseNDArray)
        if isinstance(g, RowSparseNDArray):
            if buf_sparse:
                arr._grad = g if req != "add" else \
                    row_sparse_combine(arr._grad, g)
            elif req == "add":
                # dense buffer keeps its identity (mark_variables aliasing)
                arr._grad._data = arr._grad._data + g.todense()._data
            else:
                arr._grad._data = g.todense()._data.astype(
                    arr._grad._data.dtype)
        elif buf_sparse:
            # dense cotangent into a row_sparse buffer (e.g. a hybridized
            # step after eager sparse steps): buffer stays row_sparse
            from .ndarray.sparse import cast_storage
            dense_g = _ND(jnp.asarray(g._data if isinstance(g, _ND) else g))
            rs = cast_storage(dense_g, "row_sparse")
            arr._grad = rs if req != "add" else \
                row_sparse_combine(arr._grad, rs)
        elif isinstance(g, _ND):
            # create_graph path: keep the recorded NDArray (with its tape)
            # as the grad so it can be differentiated again
            if req == "add":
                with record():
                    arr._grad = g + arr._grad
            else:
                arr._grad = g
        elif req == "add":
            arr._grad._data = arr._grad._data + g
        else:
            arr._grad._data = jnp.asarray(g, arr._grad.dtype)

    if not retain_graph:
        for node in order:
            node.vjp_fn = None
        for h in heads:
            h._ag_node = None


def _backward_walk(order, cot, keep, create_graph):
    import jax.numpy as jnp
    from .ndarray import NDArray

    for node in order:
        cts = []
        missing_all = True
        for i, (ref, (shp, dt)) in enumerate(zip(node.out_refs,
                                                 node.out_avals)):
            refs = [ref]
            if node.out_aliases:
                refs += node.out_aliases.get(i, [])
            c = None
            for r in refs:
                arr = r()
                cc = cot.pop(id(arr), None) if arr is not None else None
                if cc is not None:
                    c = cc if c is None else _add_ct(c, cc)
            if c is None:
                z = jnp.zeros(shp, dt)
                c = NDArray(z) if create_graph else z
            else:
                missing_all = False
            cts.append(c)
        if missing_all or node.vjp_fn is None:
            continue
        if create_graph and node.bwd_info is not None:
            in_cts = _record_bwd(node, cts)
        elif create_graph and node.replay is not None:
            in_cts = node.replay(cts)
        else:
            raw = [c._data if isinstance(c, NDArray) else c for c in cts]
            in_cts = node.vjp_fn(tuple(raw) if len(raw) > 1 else raw[0])
            if create_graph:
                # node lacks replay context (custom Function): gradients
                # are correct but not differentiable further
                in_cts = [NDArray(g) if g is not None else None
                          for g in in_cts]
        for inp, ict in zip(node.inputs, in_cts):
            if ict is not None:
                _accum(cot, keep, inp, ict)


def _accum(cot, keep, arr, g):
    k = id(arr)
    if k in cot:
        cot[k] = _add_ct(cot[k], g)
    else:
        cot[k] = g
    if getattr(arr, "_grad", None) is not None:
        keep[k] = (arr, cot[k])


def _add_ct(a, b):
    """Cotangent addition incl. row_sparse + row_sparse/dense mixes."""
    from .ndarray.sparse import RowSparseNDArray, row_sparse_combine

    if isinstance(a, RowSparseNDArray) and isinstance(b, RowSparseNDArray):
        return row_sparse_combine(a, b)
    if isinstance(a, RowSparseNDArray):
        return a.todense()._data + b
    if isinstance(b, RowSparseNDArray):
        return a + b.todense()._data
    return a + b


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Functional gradient (reference python/mxnet/autograd.py:273).

    With create_graph=True the returned grads are themselves recorded, enabling
    higher-order gradients (reference test_higher_order_grad.py).
    """
    from .ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    else:
        single = False
    saved = [(getattr(v, "_grad", None), getattr(v, "_grad_req", "null")) for v in variables]
    for v in variables:
        from . import nd
        v._grad = nd.zeros(v.shape, dtype=v.dtype, ctx=v.context)
        v._grad_req = "write"
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph) or create_graph,
                 train_mode=train_mode, create_graph=create_graph)
        outs = [v.grad for v in variables]
    finally:
        for v, (g, req) in zip(variables, saved):
            v._grad, v._grad_req = g, req
    return outs[0] if single else outs


class Function:
    """Custom differentiable function (reference python/mxnet/autograd.py:368).

    Subclass and implement forward(self, *inputs) and backward(self, *out_grads),
    both operating on NDArrays with autograd paused.
    """

    def __call__(self, *inputs):
        import weakref
        from .ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording():
            fn = self

            def vjp_fn(cts):
                cts = (cts,) if single else tuple(cts)
                with pause():
                    gin = fn.backward(*[NDArray(c) for c in cts])
                if isinstance(gin, NDArray):
                    gin = (gin,)
                return tuple(g._data if g is not None else None for g in gin)

            node = Node(vjp_fn, list(inputs), type(self).__name__)
            node.out_refs = [weakref.ref(o) for o in outs]
            node.out_avals = [(o.shape, o.dtype) for o in outs]
            for o in outs:
                o._ag_node = node
        return outputs
