"""Base utilities: dtypes, errors, registry.

TPU-native re-design of the reference's base layer. The reference threads a
C ABI (`include/mxnet/c_api.h`) and dmlc registries under everything; here the
"ABI" is jax/XLA, so this module only keeps the shared vocabulary: dtype
mapping (reference: 3rdparty/mshadow/mshadow/base.h MSHADOW_TYPE_SWITCH),
the framework error type (reference: dmlc/logging.h CHECK + MXGetLastError,
src/c_api/c_api_error.cc), and a tiny name->object registry (reference:
dmlc/registry.h used by operators, iterators, optimizers, metrics).
"""
from __future__ import annotations

import numpy as _np

__all__ = ["MXNetError", "MXTPUError", "Registry", "string_types", "numeric_types",
           "integer_types", "dtype_np", "dtype_name", "DTYPE_NAMES"]

string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


class MXNetError(RuntimeError):
    """Framework error type (reference: python/mxnet/base.py:75 MXNetError)."""


# Alias under the new framework's own name.
MXTPUError = MXNetError

# dtype vocabulary (reference: python/mxnet/base.py _DTYPE_NP_TO_MX / _DTYPE_MX_TO_NP).
# TPU-first addition: bfloat16 is a first-class dtype (the MXU's native input type).
DTYPE_NAMES = {
    "float32": _np.float32,
    "float64": _np.float64,
    "float16": _np.float16,
    "uint8": _np.uint8,
    "int32": _np.int32,
    "int8": _np.int8,
    "int64": _np.int64,
    "bool": _np.bool_,
    "int16": _np.int16,
    "uint16": _np.uint16,
    "uint32": _np.uint32,
    "uint64": _np.uint64,
}


def _bfloat16():
    import jax.numpy as jnp
    return jnp.bfloat16


def dtype_np(dtype):
    """Normalize a dtype spec (name/np.dtype/type) to a numpy-compatible dtype object."""
    if dtype is None:
        return _np.float32
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return _bfloat16()
        if dtype in DTYPE_NAMES:
            return DTYPE_NAMES[dtype]
        return _np.dtype(dtype).type
    return dtype


def dtype_name(dtype) -> str:
    """Canonical string name for a dtype."""
    return str(_np.dtype(dtype).name) if not _is_bf16(dtype) else "bfloat16"


def _is_bf16(dtype) -> bool:
    try:
        return "bfloat16" in str(dtype)
    except Exception:  # pragma: no cover
        return False


class Registry:
    """Name -> object registry with alias support.

    Reference: dmlc/registry.h (operators via NNVM_REGISTER_OP, 338 uses in
    src/operator/) and python/mxnet/registry.py (optimizers, metrics,
    initializers). One registry class serves all of those here.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._map: dict[str, object] = {}
        self._lower: dict[str, object] = {}  # case-insensitive fallback only

    def register(self, obj=None, name: str | None = None, aliases=()):
        def _do(o):
            key = name or getattr(o, "name", None) or o.__name__
            self._map[key] = o
            self._lower.setdefault(key.lower(), o)
            for a in aliases:
                self._map[a] = o
                self._lower.setdefault(a.lower(), o)
            return o

        return _do(obj) if obj is not None else _do

    def get(self, name: str):
        if isinstance(name, str):
            if name in self._map:
                return self._map[name]
            if name.lower() in self._lower:
                return self._lower[name.lower()]
            raise MXNetError(f"{self.kind} '{name}' is not registered "
                             f"(known: {sorted(set(k for k in self._map))[:40]}...)")
        return name

    def __contains__(self, name):
        return name in self._map or (isinstance(name, str) and name.lower() in self._lower)

    def keys(self):
        return sorted(self._map.keys())
