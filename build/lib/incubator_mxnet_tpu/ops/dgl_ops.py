"""DGL graph-sampling operator family.

Reference: src/operator/contrib/dgl_graph.cc (~1,700 LoC) — the operator
set MXNet exposed for the Deep Graph Library: CSR neighborhood sampling
(uniform + weighted), induced subgraphs, subgraph compaction, edge-id
lookup, and adjacency normalization.

TPU-native placement note: the reference registers these CPU-only
(`FComputeEx<cpu>`, dgl_graph.cc:744+) — they are data-PIPELINE operators
(random BFS with hash sets, data-dependent shapes), not accelerator
kernels. This port keeps them host-side over numpy exactly like
`cast_storage` (ndarray/sparse.py): the sampled minibatch subgraphs are
what get shipped to the chip.

Exposed as mx.nd.contrib.* (ndarray/contrib.py imports this module).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample",
           "dgl_subgraph", "edge_id", "dgl_adjacency",
           "dgl_graph_compact"]


def _csr_parts(csr):
    """(data, indices, indptr, shape) as int64/np arrays."""
    data = _np.asarray(csr.data.asnumpy()).astype(_np.int64)
    indices = _np.asarray(csr.indices.asnumpy()).astype(_np.int64)
    indptr = _np.asarray(csr.indptr.asnumpy()).astype(_np.int64)
    return data, indices, indptr, csr.shape


def _make_csr(data, indices, indptr, shape, dtype=_np.int64):
    from ..ndarray.ndarray import NDArray
    from ..ndarray.sparse import CSRNDArray
    import jax.numpy as jnp
    return CSRNDArray(NDArray(jnp.asarray(_np.asarray(data, dtype))),
                      NDArray(jnp.asarray(_np.asarray(indices, _np.int64))),
                      NDArray(jnp.asarray(_np.asarray(indptr, _np.int64))),
                      shape)


def _as_1d_int(arr):
    from ..ndarray.ndarray import NDArray
    a = arr.asnumpy() if isinstance(arr, NDArray) else _np.asarray(arr)
    return a.astype(_np.int64).reshape(-1)


def _nd(a):
    from ..ndarray.ndarray import NDArray
    import jax.numpy as jnp
    return NDArray(jnp.asarray(a))


def _sample_one(csr, seed, probability, num_hops, num_neighbor,
                max_num_vertices, rng):
    """One subgraph: the reference's SampleSubgraph BFS
    (dgl_graph.cc:529-700). Returns (ver, layer, sub_csr_parts, prob_out).

    BFS from the seeds; a vertex below the hop limit samples up to
    `num_neighbor` of its neighbors (uniform without replacement, or
    probability-weighted without replacement over the neighbor's global
    probability). Stops growing once max_num_vertices are collected."""
    data, indices, indptr, shape = _csr_parts(csr)
    seeds = _as_1d_int(seed)
    if max_num_vertices < len(seeds):
        raise MXNetError("max_num_vertices must cover the seeds")

    seen = set()
    queue = []          # (vertex, layer) in discovery order
    for s in seeds:
        if int(s) not in seen:
            seen.add(int(s))
            queue.append((int(s), 0))
    neigh = {}          # vertex -> (sampled neighbor ids, edge ids)
    idx = 0
    while idx < len(queue) and len(seen) < max_num_vertices:
        v, lvl = queue[idx]
        idx += 1
        if lvl >= num_hops:
            continue
        lo, hi = int(indptr[v]), int(indptr[v + 1])
        cols, eids = indices[lo:hi], data[lo:hi]
        if len(cols) > num_neighbor:
            if probability is None:
                pick = _np.sort(rng.choice(len(cols), num_neighbor,
                                           replace=False))
                cols, eids = cols[pick], eids[pick]
            else:
                w = probability[cols]
                total = w.sum()
                if total <= 0:
                    raise MXNetError(
                        f"non-uniform sampling: vertex {v} has "
                        f"{len(cols)} neighbors but zero total "
                        "probability mass")
                w = w / total
                pick = rng.choice(len(cols), num_neighbor, replace=False,
                                  p=w)
                # reference quirk (GetNonUniformSample, dgl_graph.cc:500):
                # vertex and edge lists are sorted INDEPENDENTLY
                cols = _np.sort(cols[pick])
                eids = _np.sort(eids[pick])
        neigh[v] = (cols, eids)
        for c in cols:
            if len(seen) >= max_num_vertices:
                break
            if int(c) not in seen:
                seen.add(int(c))
                queue.append((int(c), lvl + 1))

    order = sorted(queue)                       # sort by vertex id
    n = len(order)
    ver = _np.zeros(max_num_vertices + 1, _np.int64)
    layer = _np.zeros(max_num_vertices, _np.int64)
    ver[:n] = [v for v, _ in order]
    ver[max_num_vertices] = n
    layer[:n] = [l for _, l in order]

    sub_data, sub_indices, sub_indptr = [], [], [0]
    for i in range(max_num_vertices):
        if i < n and ver[i] in neigh:
            cols, eids = neigh[int(ver[i])]
            sub_indices.extend(cols)
            sub_data.extend(eids)
        sub_indptr.append(len(sub_data))
    prob_out = None
    if probability is not None:
        prob_out = _np.zeros(max_num_vertices, _np.float32)
        prob_out[:n] = probability[ver[:n]]
    return (ver, layer, (sub_data, sub_indices, sub_indptr,
                         (max_num_vertices, shape[1])), prob_out)


def dgl_csr_neighbor_uniform_sample(csr_matrix, *seed_arrays, num_args=None,
                                    num_hops=1, num_neighbor=2,
                                    max_num_vertices=100, rng=None,
                                    seed=None):
    """Uniform CSR neighborhood sampling
    (reference _contrib_dgl_csr_neighbor_uniform_sample,
    dgl_graph.cc:744). Returns, per seed array: a (max+1,) vertex array
    (count in the last slot), the sampled sub-CSR with ORIGINAL edge ids,
    and a (max,) per-vertex layer array — flattened into one list ordered
    [vers..., csrs..., layers...]."""
    # default keeps np.random.seed() reproducibility; pass seed= (or an
    # rng) for isolation from global RNG state
    rng = rng if rng is not None else (
        _np.random.RandomState(seed) if seed is not None else _np.random)
    outs_v, outs_c, outs_l = [], [], []
    for seed_arr in seed_arrays:
        ver, layer, parts, _ = _sample_one(csr_matrix, seed_arr, None, num_hops,
                                           num_neighbor, max_num_vertices,
                                           rng)
        outs_v.append(_nd(ver))
        outs_c.append(_make_csr(*parts))
        outs_l.append(_nd(layer))
    return outs_v + outs_c + outs_l


def dgl_csr_neighbor_non_uniform_sample(csr_matrix, probability,
                                        *seed_arrays, num_args=None,
                                        num_hops=1, num_neighbor=2,
                                        max_num_vertices=100, rng=None,
                                        seed=None):
    """Weighted sampling variant (dgl_graph.cc:838): neighbors drawn
    without replacement proportionally to `probability[neighbor]`. Adds a
    per-subgraph (max,) vertex-probability output after the CSRs."""
    # default keeps np.random.seed() reproducibility; pass seed= (or an
    # rng) for isolation from global RNG state
    rng = rng if rng is not None else (
        _np.random.RandomState(seed) if seed is not None else _np.random)
    prob = _np.asarray(
        probability.asnumpy() if hasattr(probability, "asnumpy")
        else probability, _np.float32).reshape(-1)
    outs_v, outs_c, outs_p, outs_l = [], [], [], []
    for seed_arr in seed_arrays:
        ver, layer, parts, pr = _sample_one(csr_matrix, seed_arr, prob,
                                            num_hops, num_neighbor,
                                            max_num_vertices, rng)
        outs_v.append(_nd(ver))
        outs_c.append(_make_csr(*parts))
        outs_p.append(_nd(pr))
        outs_l.append(_nd(layer))
    return outs_v + outs_c + outs_p + outs_l


def dgl_subgraph(graph, *varrays, return_mapping=False, num_args=None):
    """Induced subgraph on each (SORTED) vertex set (dgl_graph.cc:1115
    GetSubgraph): new vertex ids are positions in the vertex array, new
    edge ids number the kept edges 0..nnz-1 in row-major order; with
    return_mapping the original edge ids come back as a second CSR."""
    data, indices, indptr, shape = _csr_parts(graph)
    subs, maps = [], []
    for varr in varrays:
        v = _as_1d_int(varr)
        if not _np.all(v[:-1] <= v[1:]):
            raise MXNetError("the input vertex list has to be sorted")
        pos = {int(old): i for i, old in enumerate(v)}
        sdata, sidx, sptr, odata = [], [], [0], []
        for old in v:
            lo, hi = int(indptr[old]), int(indptr[old + 1])
            for c, e in zip(indices[lo:hi], data[lo:hi]):
                if int(c) in pos:
                    sidx.append(pos[int(c)])
                    sdata.append(len(sdata))    # new edge id, 0-based
                    odata.append(e)
            sptr.append(len(sidx))
        n = len(v)
        subs.append(_make_csr(sdata, sidx, sptr, (n, n)))
        maps.append(_make_csr(odata, sidx, sptr, (n, n)))
    return subs + maps if return_mapping else subs


def edge_id(data, u, v):
    """out[i] = data[u[i], v[i]] if the edge exists else -1
    (dgl_graph.cc:1300 _contrib_edge_id). Values keep the CSR's own data
    dtype (float edge data stays float — no int64 round trip)."""
    dat = _np.asarray(data.data.asnumpy())
    _, indices, indptr, _ = _csr_parts(data)
    uu, vv = _as_1d_int(u), _as_1d_int(v)
    out = _np.full(len(uu), -1, dat.dtype)
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = int(indptr[a]), int(indptr[a + 1])
        hit = _np.nonzero(indices[lo:hi] == b)[0]
        if len(hit):
            out[i] = dat[lo + hit[0]]
    return _nd(out)


def dgl_adjacency(data):
    """Edge-id CSR -> adjacency CSR of float32 ones (dgl_graph.cc:1376)."""
    _, indices, indptr, shape = _csr_parts(data)
    return _make_csr(_np.ones(len(indices), _np.float32), indices, indptr,
                     shape, dtype=_np.float32)


def dgl_graph_compact(*args, graph_sizes=(), return_mapping=False,
                      num_args=None):
    """Strip the empty tail rows/columns a neighbor-sample CSR carries and
    renumber columns to subgraph-local ids (dgl_graph.cc:1551
    CompactSubgraph). args = graphs..., vertex_arrays... (same count);
    graph_sizes holds each subgraph's true vertex count. New edge ids
    number kept edges 0..nnz-1; return_mapping returns the original ids
    as a second CSR."""
    if isinstance(graph_sizes, int):
        graph_sizes = (graph_sizes,)
    num_g = len(args) // 2
    if len(args) != 2 * num_g or num_g == 0:
        raise MXNetError("dgl_graph_compact needs graphs + vertex arrays")
    if len(graph_sizes) != num_g:
        raise MXNetError("graph_sizes must have one entry per graph")
    subs, maps = [], []
    for g, varr, size in zip(args[:num_g], args[num_g:], graph_sizes):
        size = int(size)
        data, indices, indptr, shape = _csr_parts(g)
        vids = _as_1d_int(varr)
        if int(vids[-1]) != size:
            raise MXNetError("vertex array count does not match graph_sizes")
        pos = {int(old): i for i, old in enumerate(vids[:size])}
        sdata, sidx, sptr, odata = [], [], [0], []
        for r in range(size):
            lo, hi = int(indptr[r]), int(indptr[r + 1])
            for c, e in zip(indices[lo:hi], data[lo:hi]):
                if int(c) not in pos:
                    raise MXNetError(f"column id {int(c)} not in the "
                                     "vertex array")
                sidx.append(pos[int(c)])
                sdata.append(len(sdata))
                odata.append(e)
            sptr.append(len(sidx))
        subs.append(_make_csr(sdata, sidx, sptr, (size, size)))
        maps.append(_make_csr(odata, sidx, sptr, (size, size)))
    return subs + maps if return_mapping else subs
