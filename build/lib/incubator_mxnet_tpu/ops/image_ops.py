"""Device-side image operators: the `nd.image.*` / `mx.sym.image.*` family.

Reference: src/operator/image/image_random.cc (to_tensor, normalize, the
flip/brightness/contrast/saturation/hue/color-jitter/lighting augmenters),
src/operator/image/crop.cc (_image_crop), src/operator/image/resize-inl.h
(_image_resize). The reference runs these as CPU/GPU kernels so augmentation
can fuse into the compiled graph; here each is a pure jax function, so a
transform pipeline jit-compiles into ONE XLA program (and can run on-chip,
overlapping with the train step — the TPU answer to the reference's
multi-worker CPU augmentation).

Layout convention matches the reference: HWC (or NHWC batched) uint8/float
in [0,255] for the augmenters; to_tensor converts to CHW float32 [0,1];
normalize operates on CHW/NCHW.

Known deviation: the reference's AdjustSaturationImpl computes its gray
value with `gray = px*coef` in a loop (image_random-inl.h:757 — assignment,
not accumulation), i.e. gray ends up as B*0.114 only. We compute the
documented ITU-R gray (0.299R + 0.587G + 0.114B), matching torchvision and
GluonCV's own python transforms.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .registry import register

import jax
import jax.numpy as jnp
from jax import lax

_GRAY = (0.299, 0.587, 0.114)


def _saturate(val, like):
    """saturate_cast: clamp when the output dtype is integral."""
    if jnp.issubdtype(like.dtype, jnp.integer):
        info = jnp.iinfo(like.dtype)
        return jnp.clip(jnp.round(val), info.min, info.max).astype(like.dtype)
    return val.astype(like.dtype)


@register(name="_image_to_tensor", aliases=("to_tensor",))
def to_tensor(data):
    """(H,W,C)->(C,H,W) float32/255 ((N,H,W,C) batched alike) — reference
    image_random.cc:41."""
    if data.ndim == 3:
        perm = (2, 0, 1)
    elif data.ndim == 4:
        perm = (0, 3, 1, 2)
    else:
        raise MXNetError(f"to_tensor: expected 3D/4D HWC input, got "
                         f"{data.ndim}D")
    return jnp.transpose(data, perm).astype(jnp.float32) / 255.0


@register(name="_image_normalize", aliases=("normalize",))
def normalize(data, *, mean=(0.0,), std=(1.0,)):
    """Channel-wise (x - mean) / std on CHW or NCHW float input —
    reference image_random.cc:105."""
    mean = tuple(mean) if isinstance(mean, (tuple, list)) else (float(mean),)
    std = tuple(std) if isinstance(std, (tuple, list)) else (float(std),)
    c_ax = data.ndim - 3
    c = data.shape[c_ax]
    m = jnp.asarray((mean * c)[:c] if len(mean) == 1 else mean,
                    data.dtype)
    s = jnp.asarray((std * c)[:c] if len(std) == 1 else std, data.dtype)
    shape = [1] * data.ndim
    shape[c_ax] = c
    return (data - m.reshape(shape)) / s.reshape(shape)


@register(name="_image_flip_left_right", aliases=("flip_left_right",),
          nondiff=True)
def flip_left_right(data):
    return jnp.flip(data, axis=data.ndim - 2)


@register(name="_image_flip_top_bottom", aliases=("flip_top_bottom",),
          nondiff=True)
def flip_top_bottom(data):
    return jnp.flip(data, axis=data.ndim - 3)


@register(name="_image_random_flip_left_right",
          aliases=("random_flip_left_right",), stateful=True, nondiff=True)
def random_flip_left_right(data, *, p=0.5, rng=None):
    return jnp.where(jax.random.uniform(rng) < p,
                     jnp.flip(data, axis=data.ndim - 2), data)


@register(name="_image_random_flip_top_bottom",
          aliases=("random_flip_top_bottom",), stateful=True, nondiff=True)
def random_flip_top_bottom(data, *, p=0.5, rng=None):
    return jnp.where(jax.random.uniform(rng) < p,
                     jnp.flip(data, axis=data.ndim - 3), data)


def _adjust_brightness(data, alpha):
    return _saturate(data.astype(jnp.float32) * alpha, data)


def _adjust_contrast(data, alpha):
    x = data.astype(jnp.float32)
    if data.shape[-1] >= 3:
        gray = (x[..., 0] * _GRAY[0] + x[..., 1] * _GRAY[1]
                + x[..., 2] * _GRAY[2])
    else:
        gray = x[..., 0]
    # per-image mean over H,W (vectorized over any leading batch dims)
    beta = (1.0 - alpha) * jnp.mean(gray, axis=(-2, -1), keepdims=True)
    return _saturate(x * alpha + beta[..., None], data)


def _adjust_saturation(data, alpha):
    if data.shape[-1] < 3:
        return data
    x = data.astype(jnp.float32)
    gray = (x[..., 0] * _GRAY[0] + x[..., 1] * _GRAY[1]
            + x[..., 2] * _GRAY[2])
    return _saturate(x * alpha + gray[..., None] * (1.0 - alpha), data)


def _adjust_hue(data, alpha):
    """Rotate hue by alpha*360 degrees through HSV (reference
    image_random-inl.h AdjustHueImpl's HLS round-trip; HSV yields the
    same hue rotation and vectorizes cleanly)."""
    if data.shape[-1] < 3:
        return data
    x = data.astype(jnp.float32) / 255.0
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx_ = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    diff = mx_ - mn
    safe = jnp.where(diff == 0, 1.0, diff)
    h = jnp.where(
        mx_ == r, (g - b) / safe,
        jnp.where(mx_ == g, 2.0 + (b - r) / safe, 4.0 + (r - g) / safe))
    h = jnp.where(diff == 0, 0.0, h) / 6.0
    h = jnp.mod(h + alpha, 1.0)
    s = jnp.where(mx_ == 0, 0.0, diff / jnp.where(mx_ == 0, 1.0, mx_))
    v = mx_
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r2 = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g2 = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b2 = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    out = jnp.stack([r2, g2, b2], axis=-1) * 255.0
    return _saturate(out, data)


# eigenvalue * eigenvector products for AlexNet-style PCA lighting
# (reference image_random-inl.h:1005 AdjustLightingImpl `eig`)
_LIGHT_EIG = _np.array(
    [[55.46 * -0.5675, 4.794 * 0.7192, 1.148 * 0.4009],
     [55.46 * -0.5808, 4.794 * -0.0045, 1.148 * -0.8140],
     [55.46 * -0.5836, 4.794 * -0.6948, 1.148 * 0.4203]], _np.float32)


def _adjust_lighting(data, alpha):
    if data.shape[-1] < 3:
        return data
    pca = jnp.asarray(_LIGHT_EIG) @ jnp.asarray(alpha, jnp.float32)
    return _saturate(data.astype(jnp.float32) + pca, data)


@register(name="_image_random_brightness", aliases=("random_brightness",),
          stateful=True, nondiff=True)
def random_brightness(data, *, min_factor, max_factor, rng=None):
    a = jax.random.uniform(rng, minval=min_factor, maxval=max_factor)
    return _adjust_brightness(data, a)


@register(name="_image_random_contrast", aliases=("random_contrast",),
          stateful=True, nondiff=True)
def random_contrast(data, *, min_factor, max_factor, rng=None):
    a = jax.random.uniform(rng, minval=min_factor, maxval=max_factor)
    return _adjust_contrast(data, a)


@register(name="_image_random_saturation", aliases=("random_saturation",),
          stateful=True, nondiff=True)
def random_saturation(data, *, min_factor, max_factor, rng=None):
    a = jax.random.uniform(rng, minval=min_factor, maxval=max_factor)
    return _adjust_saturation(data, a)


@register(name="_image_random_hue", aliases=("random_hue",), stateful=True,
          nondiff=True)
def random_hue(data, *, min_factor, max_factor, rng=None):
    a = jax.random.uniform(rng, minval=min_factor, maxval=max_factor)
    return _adjust_hue(data, a)


@register(name="_image_random_color_jitter", aliases=("random_color_jitter",),
          stateful=True, nondiff=True)
def random_color_jitter(data, *, brightness=0.0, contrast=0.0,
                        saturation=0.0, hue=0.0, rng=None):
    """Reference image_random-inl.h:944 RandomColorJitter: apply each
    enabled adjustment with an independent uniform factor. The reference
    shuffles application order per call; a fixed order keeps the op
    jittable and the distributions are near-identical."""
    keys = jax.random.split(rng, 4)
    out = data
    if brightness > 0:
        a = jax.random.uniform(keys[0], minval=max(0.0, 1 - brightness),
                               maxval=1 + brightness)
        out = _adjust_brightness(out, a)
    if contrast > 0:
        a = jax.random.uniform(keys[1], minval=max(0.0, 1 - contrast),
                               maxval=1 + contrast)
        out = _adjust_contrast(out, a)
    if saturation > 0:
        a = jax.random.uniform(keys[2], minval=max(0.0, 1 - saturation),
                               maxval=1 + saturation)
        out = _adjust_saturation(out, a)
    if hue > 0:
        a = jax.random.uniform(keys[3], minval=-hue, maxval=hue)
        out = _adjust_hue(out, a)
    return out


@register(name="_image_adjust_lighting", aliases=("adjust_lighting",),
          nondiff=True)
def adjust_lighting(data, *, alpha):
    """Reference image_random.cc:252 — AlexNet PCA lighting with fixed
    alpha triple."""
    return _adjust_lighting(data, tuple(alpha))


@register(name="_image_random_lighting", aliases=("random_lighting",),
          stateful=True, nondiff=True)
def random_lighting(data, *, alpha_std=0.05, rng=None):
    a = jax.random.normal(rng, (3,)) * alpha_std
    return _adjust_lighting(data, a)


@register(name="_image_crop", aliases=("crop",), nondiff=True)
def image_crop(data, *, x, y, width, height):
    """Reference src/operator/image/crop.cc:37: HWC/NHWC crop at
    (x,y) with size (width,height)."""
    if data.ndim == 3:
        return lax.dynamic_slice(
            data, (y, x, 0), (height, width, data.shape[2]))
    return lax.dynamic_slice(
        data, (0, y, x, 0), (data.shape[0], height, width, data.shape[3]))


@register(name="_image_resize", aliases=("resize",), nondiff=True)
def image_resize(data, *, size=(), keep_ratio=False, interp=1):
    """Reference src/operator/image/resize-inl.h: resize HWC/NHWC.
    size = int (short edge if keep_ratio else square) or (w, h).
    interp: 0 nearest, 1 bilinear (others map to bilinear — XLA resize
    supports these two natively; cubic/lanczos would need a custom
    kernel for no accuracy the zoo models care about)."""
    hw = data.shape[-3:-1]
    if isinstance(size, int):
        size = (size,)
    size = tuple(size)
    if len(size) == 1:
        if keep_ratio:
            h, w = hw
            if h < w:
                new_h, new_w = size[0], max(1, int(round(w * size[0] / h)))
            else:
                new_h, new_w = max(1, int(round(h * size[0] / w))), size[0]
        else:
            new_h = new_w = size[0]
    else:
        new_w, new_h = size[0], size[1]
    method = "nearest" if interp == 0 else "bilinear"
    out_shape = data.shape[:-3] + (new_h, new_w, data.shape[-1])
    out = jax.image.resize(data.astype(jnp.float32), out_shape, method)
    return _saturate(out, data)
