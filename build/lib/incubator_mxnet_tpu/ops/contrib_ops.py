"""Contrib ops: SSD detection family, ROI align, NMS, misc.

Reference: src/operator/contrib/ (21,184 LoC) — multibox_prior/target/
detection.cc (SSD anchors/matching/decode), bounding_box.cc (box_nms),
roi_align.cc, adaptive_avg_pooling.cc, index_copy.cc.

TPU-native design: everything is static-shape. NMS is a fixed-N greedy
sweep (pairwise IoU matrix + lax.fori_loop mask updates) instead of the
reference's dynamic workspace sort; suppressed entries become -1 exactly
like the reference's output convention, so downstream slicing code ports
unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

__all__ = []


# ---------------------------------------------------------------------------
# MultiBoxPrior (reference multibox_prior.cc)
# ---------------------------------------------------------------------------

@register(name="_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",),
          nondiff=True)
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes per feature-map cell: len(sizes)+len(ratios)-1 anchors,
    corner format, normalized. Returns (1, H*W*A, 4)."""
    H, W = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1)  # H, W, 2

    whs = []
    for s in sizes:
        whs.append((s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])))
    for r in ratios[1:]:
        whs.append((sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r)))
    wh = jnp.asarray(whs, jnp.float32)  # A, 2 (w, h)

    c = cyx[:, :, None, :]  # H, W, 1, 2 (cy, cx)
    half_w = wh[None, None, :, 0] / 2
    half_h = wh[None, None, :, 1] / 2
    xmin = c[..., 1] - half_w
    ymin = c[..., 0] - half_h
    xmax = c[..., 1] + half_w
    ymax = c[..., 0] + half_h
    out = jnp.stack([xmin, ymin, xmax, ymax], -1).reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _iou_corner(a, b):
    """Pairwise IoU; a: (N, 4), b: (M, 4) corner format -> (N, M)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, -1)
    bx1, by1, bx2, by2 = (b[:, i] for i in range(4))
    ix1 = jnp.maximum(ax1, bx1)
    iy1 = jnp.maximum(ay1, by1)
    ix2 = jnp.minimum(ax2, bx2)
    iy2 = jnp.minimum(ay2, by2)
    iw = jnp.maximum(ix2 - ix1, 0)
    ih = jnp.maximum(iy2 - iy1, 0)
    inter = iw * ih
    area_a = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0)
    area_b = jnp.maximum((bx2 - bx1) * (by2 - by1), 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


# ---------------------------------------------------------------------------
# MultiBoxTarget (reference multibox_target.cc)
# ---------------------------------------------------------------------------

@register(name="_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",),
          nondiff=True)
def multibox_target(anchor, label, cls_pred, *, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets. anchor (1,N,4); label (B,M,5) rows
    [cls, xmin, ymin, xmax, ymax] padded with -1; cls_pred (B,C,N).
    Returns (box_target (B,N*4), box_mask (B,N*4), cls_target (B,N)).

    With negative_mining_ratio > 0, unmatched anchors are hard-mined by
    foreground confidence: the top max(ratio*num_pos, minimum_negative_
    samples) stay background (0), the rest get ignore_label (reference
    multibox_target.cc hard-negative path)."""
    anchors = anchor[0]  # N, 4
    N = anchors.shape[0]
    v = jnp.asarray(variances, jnp.float32)

    def per_batch(lab, pred):
        valid = lab[:, 0] >= 0  # M
        gt = lab[:, 1:5]
        ious = _iou_corner(anchors, gt)  # N, M
        ious = jnp.where(valid[None, :], ious, -1.0)
        best_gt = jnp.argmax(ious, axis=1)          # per-anchor best gt
        best_iou = jnp.max(ious, axis=1)
        # force-match: each gt's best anchor. Padding gts must not scatter —
        # their argmax lands on anchor 0 and duplicate-index .set would let
        # the padding row win; route them to index N and drop.
        best_anchor = jnp.argmax(ious, axis=0)      # M
        scatter_to = jnp.where(valid, best_anchor, N)
        forced = jnp.zeros((N,), bool).at[scatter_to].set(True, mode="drop")
        forced_gt = jnp.full((N,), -1, jnp.int32).at[scatter_to].set(
            jnp.arange(lab.shape[0], dtype=jnp.int32), mode="drop")
        matched = forced | (best_iou >= overlap_threshold)
        gt_idx = jnp.where(forced, forced_gt, best_gt)

        m_gt = gt[gt_idx]                    # N, 4
        # encode (reference: center-offset normalized by variances)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(m_gt[:, 2] - m_gt[:, 0], 1e-8)
        gh = jnp.maximum(m_gt[:, 3] - m_gt[:, 1], 1e-8)
        gcx = (m_gt[:, 0] + m_gt[:, 2]) / 2
        gcy = (m_gt[:, 1] + m_gt[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / v[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / v[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / v[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / v[3]
        box_t = jnp.stack([tx, ty, tw, th], -1)      # N, 4
        box_t = jnp.where(matched[:, None], box_t, 0.0)
        mask = jnp.where(matched[:, None],
                         jnp.ones((N, 4), jnp.float32), 0.0)
        cls_t = jnp.where(matched, lab[gt_idx, 0] + 1, 0.0)
        if negative_mining_ratio > 0:
            # hard negatives: unmatched anchors whose best IoU stays under
            # negative_mining_thresh, ranked by foreground confidence
            candidate = (~matched) & (best_iou < negative_mining_thresh)
            hardness = jnp.max(pred[1:], axis=0)  # best non-bg score per anchor
            ranked = jnp.argsort(jnp.where(candidate, -hardness, jnp.inf))
            rank = jnp.zeros((N,), jnp.int32).at[ranked].set(jnp.arange(N))
            num_pos = jnp.sum(matched)
            keep_n = jnp.maximum(negative_mining_ratio * num_pos,
                                 minimum_negative_samples)
            kept_neg = candidate & (rank < keep_n)
            cls_t = jnp.where(matched, cls_t,
                              jnp.where(kept_neg, 0.0, ignore_label))
        return box_t.reshape(-1), mask.reshape(-1), cls_t

    box_target, box_mask, cls_target = jax.vmap(per_batch)(label, cls_pred)
    return box_target, box_mask, cls_target


# ---------------------------------------------------------------------------
# greedy NMS core (fixed N, lax loop)
# ---------------------------------------------------------------------------

def _greedy_nms_keep(boxes, scores, valid, iou_thresh, same_class):
    """Returns bool keep mask; greedy in score order."""
    N = boxes.shape[0]
    order = jnp.argsort(-scores)
    iou = _iou_corner(boxes[order], boxes[order])
    cls_ok = same_class[jnp.ix_(order, order)] if same_class is not None \
        else jnp.ones((N, N), bool)
    valid_o = valid[order]

    def body(i, keep):
        k_i = keep[i] & valid_o[i]
        row = (iou[i] >= iou_thresh) & cls_ok[i] & k_i
        row = row & (jnp.arange(N) > i)  # only suppress lower-scored boxes
        return keep & ~row

    keep_o = lax.fori_loop(0, N, body, valid_o)
    keep = jnp.zeros((N,), bool).at[order].set(keep_o)
    return keep


# ---------------------------------------------------------------------------
# box_nms (reference bounding_box.cc)
# ---------------------------------------------------------------------------

@register(name="_contrib_box_nms", aliases=("box_nms",), nondiff=True)
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Suppressed rows become -1 (reference convention). data: (..., N, K)."""
    if in_format != "corner":
        raise MXNetError("only corner format is implemented")

    def one(mat):
        scores = mat[:, score_index]
        boxes = mat[:, coord_start:coord_start + 4]
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= mat[:, id_index] != background_id
        if id_index >= 0 and not force_suppress:
            ids = mat[:, id_index]
            same = ids[:, None] == ids[None, :]
        else:
            same = jnp.ones((mat.shape[0],) * 2, bool)
        if topk > 0:
            # reference semantics: NMS only considers the top-k scored
            # candidates; the rest are suppressed outright
            order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
            rank = jnp.zeros_like(order).at[order].set(
                jnp.arange(order.shape[0]))
            valid &= rank < topk
        keep = _greedy_nms_keep(boxes, scores, valid, overlap_thresh, same)
        return jnp.where(keep[:, None], mat, -jnp.ones_like(mat))

    flat = data.reshape((-1,) + data.shape[-2:])
    out = jax.vmap(one)(flat)
    return out.reshape(data.shape)


# ---------------------------------------------------------------------------
# MultiBoxDetection (reference multibox_detection.cc)
# ---------------------------------------------------------------------------

@register(name="_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",),
          nondiff=True)
def multibox_detection(cls_prob, loc_pred, anchor, *, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + per-class NMS. cls_prob (B,C,N), loc_pred (B,N*4),
    anchor (1,N,4) -> (B, N, 6) rows [cls_id, score, x1, y1, x2, y2];
    suppressed rows are -1."""
    B, C, N = cls_prob.shape
    v = jnp.asarray(variances, jnp.float32)
    anchors = anchor[0]
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def per_batch(probs, loc):
        loc = loc.reshape(N, 4)
        cx = loc[:, 0] * v[0] * aw + acx
        cy = loc[:, 1] * v[1] * ah + acy
        w = jnp.exp(loc[:, 2] * v[2]) * aw
        h = jnp.exp(loc[:, 3] * v[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor (reference picks argmax)
        fg = jnp.concatenate([probs[:background_id],
                              probs[background_id + 1:]], 0) \
            if 0 <= background_id < C else probs
        cls_id = jnp.argmax(fg, 0)
        # translate back to original class index space (background removed)
        cls_id = jnp.where(cls_id >= background_id, cls_id + 1, cls_id) \
            if 0 <= background_id < C else cls_id
        score = jnp.max(fg, 0)
        valid = score > threshold
        out_cls = jnp.where(valid, (cls_id - 1).astype(jnp.float32), -1.0) \
            if background_id == 0 else \
            jnp.where(valid, cls_id.astype(jnp.float32), -1.0)
        same = (out_cls[:, None] == out_cls[None, :]) \
            if not force_suppress else jnp.ones((N, N), bool)
        if nms_topk > 0:
            order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
            rank = jnp.zeros_like(order).at[order].set(jnp.arange(N))
            valid &= rank < nms_topk
        keep = _greedy_nms_keep(boxes, score, valid, nms_threshold, same)
        row = jnp.concatenate([out_cls[:, None], score[:, None], boxes], -1)
        return jnp.where(keep[:, None], row, -jnp.ones_like(row))

    return jax.vmap(per_batch)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# ROIAlign (reference roi_align.cc) + legacy ROIPooling
# ---------------------------------------------------------------------------

@register(name="_contrib_ROIAlign", aliases=("ROIAlign",))
def roi_align(data, rois, *, pooled_size, spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """Bilinear ROI align. data (B,C,H,W); rois (R,5) [bidx,x1,y1,x2,y2]
    -> (R, C, PH, PW)."""
    if position_sensitive:
        raise MXNetError("position_sensitive ROIAlign is not implemented")
    PH, PW = pooled_size
    B, C, H, W = data.shape
    s = 2 if sample_ratio <= 0 else sample_ratio
    offset = 0.5 if aligned else 0.0

    def bilinear(img, y, x):
        # img: (C, H, W); y, x scalar grids
        y = jnp.clip(y, 0.0, H - 1.0)
        x = jnp.clip(x, 0.0, W - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        wy = y - y0
        wx = x - x0
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1]
        v10 = img[:, y1, x0]
        v11 = img[:, y1, x1]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                v10 * wy * (1 - wx) + v11 * wy * wx)

    def per_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w = rw / PW
        bin_h = rh / PH
        # s x s sample grid per bin, averaged
        iy = jnp.arange(PH, dtype=jnp.float32)
        ix = jnp.arange(PW, dtype=jnp.float32)
        sy = (jnp.arange(s, dtype=jnp.float32) + 0.5) / s
        sx = (jnp.arange(s, dtype=jnp.float32) + 0.5) / s
        ys = y1 + (iy[:, None] + sy[None, :]) * bin_h  # PH, s
        xs = x1 + (ix[:, None] + sx[None, :]) * bin_w  # PW, s
        yy = ys.reshape(-1)  # PH*s
        xx = xs.reshape(-1)  # PW*s
        grid = jax.vmap(lambda y: jax.vmap(lambda x: bilinear(img, y, x))(xx))(yy)
        # grid: (PH*s, PW*s, C) -> average each s x s block
        grid = grid.reshape(PH, s, PW, s, C).mean((1, 3))
        return jnp.transpose(grid, (2, 0, 1))

    return jax.vmap(per_roi)(rois)


@register(name="ROIPooling", aliases=("roi_pooling",))
def roi_pooling(data, rois, *, pooled_size, spatial_scale=1.0):
    """Legacy max ROI pooling (reference src/operator/roi_pooling.cc),
    implemented as dense-grid max over each bin."""
    PH, PW = pooled_size
    B, C, H, W = data.shape

    def per_roi2(roi):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)

        def bin_val(py, px):
            sy = y1 + py * rh / PH
            ey = y1 + (py + 1) * rh / PH
            sx = x1 + px * rw / PW
            ex = x1 + (px + 1) * rw / PW
            my = (ys >= jnp.floor(sy)) & (ys < jnp.maximum(jnp.ceil(ey),
                                                           jnp.floor(sy) + 1))
            mx = (xs >= jnp.floor(sx)) & (xs < jnp.maximum(jnp.ceil(ex),
                                                           jnp.floor(sx) + 1))
            mask = my[:, None] & mx[None, :]
            return jnp.where(mask[None], img, -jnp.inf).max((1, 2))

        pys = jnp.arange(PH, dtype=jnp.float32)
        pxs = jnp.arange(PW, dtype=jnp.float32)
        grid = jax.vmap(lambda py: jax.vmap(lambda px: bin_val(py, px))(pxs))(pys)
        return jnp.transpose(grid, (2, 0, 1))

    return jax.vmap(per_roi2)(rois)


# ---------------------------------------------------------------------------
# misc contrib (reference adaptive_avg_pooling.cc, index_copy.cc)
# ---------------------------------------------------------------------------

@register(name="_contrib_AdaptiveAvgPooling2D",
          aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling(data, *, output_size=1):
    """Reference contrib/adaptive_avg_pooling.cc."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    B, C, H, W = data.shape
    # integral-image bins; floor(start)/ceil(end) spans always cover >= 1
    # pixel so output_size > input size (adaptive upsampling) stays finite
    idx_h = jnp.arange(oh, dtype=jnp.float32)
    idx_w = jnp.arange(ow, dtype=jnp.float32)
    ys0 = jnp.floor(idx_h * H / oh).astype(jnp.int32)
    ys1 = jnp.ceil((idx_h + 1) * H / oh).astype(jnp.int32)
    xs0 = jnp.floor(idx_w * W / ow).astype(jnp.int32)
    xs1 = jnp.ceil((idx_w + 1) * W / ow).astype(jnp.int32)
    cum = jnp.cumsum(jnp.cumsum(
        jnp.pad(data, ((0, 0), (0, 0), (1, 0), (1, 0))), axis=2), axis=3)
    area = ((ys1 - ys0)[:, None] * (xs1 - xs0)[None, :]).astype(data.dtype)
    out = (cum[:, :, ys1, :][:, :, :, xs1] -
           cum[:, :, ys0, :][:, :, :, xs1] -
           cum[:, :, ys1, :][:, :, :, xs0] +
           cum[:, :, ys0, :][:, :, :, xs0])
    return out / area


@register(name="_contrib_index_copy", aliases=("index_copy",))
def index_copy(old, index, new):
    """Reference contrib/index_copy.cc: rows of old replaced by new at
    index."""
    return old.at[index.astype(jnp.int32)].set(new.astype(old.dtype))


@register(name="_contrib_box_iou", aliases=("box_iou",), nondiff=True)
def box_iou(lhs, rhs, *, format="corner"):
    """Reference bounding_box.cc box_iou."""
    if format != "corner":
        raise MXNetError("only corner format is implemented")
    shape_l = lhs.shape[:-1]
    shape_r = rhs.shape[:-1]
    out = _iou_corner(lhs.reshape(-1, 4), rhs.reshape(-1, 4))
    return out.reshape(shape_l + shape_r)

# ---------------------------------------------------------------------------
# RPN Proposal / MultiProposal (reference proposal.cc, multi_proposal.cc):
# anchors + bbox deltas -> clip -> min-size filter -> top-pre_nms -> NMS ->
# top-post_nms. Static-shape: scores of filtered boxes are -inf, output is
# always (N*post_nms, 5) padded by repeating the best box (reference pads
# from the kept list).
# ---------------------------------------------------------------------------

def _base_anchors(scales, ratios, stride):
    """Anchor boxes around (0,0) cell of size `stride` (reference
    proposal-inl.h GenerateAnchors: ratio enumeration then scales,
    base_size=stride)."""
    base = float(stride)
    cx = (base - 1) / 2.0
    cy = (base - 1) / 2.0
    anchors = []
    for r in ratios:
        size = base * base
        size_ratio = size / r
        ws = round(size_ratio ** 0.5)
        hs = round(ws * r)
        for s in scales:
            w = ws * s
            h = hs * s
            anchors.append([cx - (w - 1) / 2.0, cy - (h - 1) / 2.0,
                            cx + (w - 1) / 2.0, cy + (h - 1) / 2.0])
    return jnp.asarray(anchors, jnp.float32)          # (A, 4)


def _proposal_impl(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n,
                   rpn_post_nms_top_n, threshold, rpn_min_size, scales,
                   ratios, feature_stride, output_score):
    N, twoA, H, W = cls_prob.shape
    A = twoA // 2
    base = _base_anchors(tuple(scales), tuple(ratios), feature_stride)
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    shift = jnp.stack(jnp.broadcast_arrays(
        sx[None, :, None], sy[:, None, None]), -1)    # (H, W, 1, 2)? build 4
    # anchor grid: (H, W, A, 4)
    shifts = jnp.concatenate([shift, shift], -1)      # x1 y1 x2 y2 shifts
    anchors = base[None, None] + shifts
    total = H * W * A
    pre = min(int(rpn_pre_nms_top_n), total) if rpn_pre_nms_top_n > 0 else total
    post = int(rpn_post_nms_top_n)

    def per_image(scores_fg, deltas, info):
        # scores_fg: (A, H, W); deltas: (4A, H, W)
        sc = jnp.transpose(scores_fg, (1, 2, 0)).reshape(-1)       # HWA
        dl = jnp.transpose(deltas.reshape(A, 4, H, W), (2, 3, 0, 1)
                           ).reshape(-1, 4)
        anc = anchors.reshape(-1, 4)
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + 0.5 * (aw - 1.0)
        acy = anc[:, 1] + 0.5 * (ah - 1.0)
        cx = dl[:, 0] * aw + acx
        cy = dl[:, 1] * ah + acy
        w = jnp.exp(dl[:, 2]) * aw
        h = jnp.exp(dl[:, 3]) * ah
        boxes = jnp.stack([cx - 0.5 * (w - 1.0), cy - 0.5 * (h - 1.0),
                           cx + 0.5 * (w - 1.0), cy + 0.5 * (h - 1.0)], -1)
        im_h, im_w, im_scale = info[0], info[1], info[2]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1.0),
                           jnp.clip(boxes[:, 1], 0, im_h - 1.0),
                           jnp.clip(boxes[:, 2], 0, im_w - 1.0),
                           jnp.clip(boxes[:, 3], 0, im_h - 1.0)], -1)
        min_sz = rpn_min_size * im_scale
        bw = boxes[:, 2] - boxes[:, 0] + 1.0
        bh = boxes[:, 3] - boxes[:, 1] + 1.0
        valid = (bw >= min_sz) & (bh >= min_sz)
        sc = jnp.where(valid, sc, -jnp.inf)
        # top-pre_nms candidates only
        top_sc, top_idx = lax.top_k(sc, pre)
        top_boxes = boxes[top_idx]
        keep = _greedy_nms_keep(top_boxes, top_sc,
                                jnp.isfinite(top_sc), threshold, None)
        # order kept boxes first (stable by score: top_k already sorted)
        kept_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
        slot = jnp.where(keep, kept_rank, pre)
        out_boxes = jnp.zeros((pre + 1, 4), boxes.dtype)
        out_sc = jnp.full((pre + 1,), -jnp.inf, sc.dtype)
        out_boxes = out_boxes.at[slot].set(top_boxes)
        out_sc = out_sc.at[slot].set(jnp.where(keep, top_sc, -jnp.inf))
        n_kept = jnp.sum(keep.astype(jnp.int32))
        idx = jnp.arange(post)
        # pad by repeating the first (best) kept box, reference-style
        src = jnp.where(idx < n_kept, idx, 0)
        return out_boxes[src], out_sc[src]

    fg = cls_prob[:, A:]
    boxes, scores = jax.vmap(per_image)(fg, bbox_pred, im_info)
    bidx = jnp.repeat(jnp.arange(N, dtype=boxes.dtype), post)[:, None]
    rois = jnp.concatenate([bidx, boxes.reshape(N * post, 4)], -1)
    if output_score:
        return rois, scores.reshape(N * post, 1)
    return rois


@register(name="_contrib_Proposal",
          aliases=("Proposal", "_contrib_MultiProposal", "MultiProposal"),
          nondiff=True)
def proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """RPN proposals (reference proposal.cc; multi_proposal.cc is the same
    math vmapped over the batch — this implementation is batched already,
    so MultiProposal is an alias)."""
    if iou_loss:
        raise MXNetError("iou_loss Proposal variant is not implemented")
    return _proposal_impl(
        cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=rpn_pre_nms_top_n,
        rpn_post_nms_top_n=rpn_post_nms_top_n, threshold=threshold,
        rpn_min_size=rpn_min_size, scales=scales, ratios=ratios,
        feature_stride=feature_stride, output_score=output_score)


# ---------------------------------------------------------------------------
# Position-sensitive ROI pooling (reference psroi_pooling.cc) and the
# deformable variant (deformable_psroi_pooling.cc). Bins are averaged over a
# fixed sample grid (the deformable reference itself uses sample_per_part
# fixed samples; for plain PSROI the reference averages integer pixels —
# the fixed-grid average is the static-shape equivalent).
# ---------------------------------------------------------------------------

def _psroi_impl(data, rois, trans, *, spatial_scale, output_dim, pooled_size,
                group_size, part_size=0, sample_per_part=2, trans_std=0.0):
    B, C, H, W = data.shape
    P = int(pooled_size)
    G = int(group_size) or P
    part = int(part_size) or P
    sp = max(1, int(sample_per_part))
    n_cls = 1 if trans is None else trans.shape[1] // 2
    ch_per_cls = output_dim // n_cls

    def per_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]
        # reference: round then offset by 0.5 pixel, width/height >= 0.1
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / P
        bin_h = rh / P
        iy = jnp.arange(P, dtype=jnp.float32)
        ix = jnp.arange(P, dtype=jnp.float32)
        ss = (jnp.arange(sp, dtype=jnp.float32) + 0.5) / sp
        # per output bin (ph, pw): sample grid, per-class trans offsets
        gy = jnp.clip((iy * G / P).astype(jnp.int32), 0, G - 1)     # (P,)
        gx = jnp.clip((ix * G / P).astype(jnp.int32), 0, G - 1)
        py = jnp.clip((iy * part / P).astype(jnp.int32), 0, part - 1)
        px = jnp.clip((ix * part / P).astype(jnp.int32), 0, part - 1)

        def one_class(cls_id):
            if trans is None:
                tx = jnp.zeros((P, P))
                ty = jnp.zeros((P, P))
            else:
                # per-bin (part_h, part_w) offsets, like the reference's
                # bottom_trans[...part_h...part_w] read
                tx = tr[2 * cls_id][py[:, None], px[None, :]] * trans_std
                ty = tr[2 * cls_id + 1][py[:, None], px[None, :]] * trans_std
            # full per-bin sample grids (P, P, sp): the trans offset varies
            # with BOTH bin indices, so the grid is not separable
            ys = (y1 + iy[:, None, None] * bin_h
                  + ss[None, None, :] * bin_h + ty[:, :, None] * rh)
            xs = (x1 + ix[None, :, None] * bin_w
                  + ss[None, None, :] * bin_w + tx[:, :, None] * rw)
            ys = jnp.clip(ys, 0.0, H - 1.0)                     # (P, P, sp)
            xs = jnp.clip(xs, 0.0, W - 1.0)
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs).astype(jnp.int32)
            wy = ys - y0
            wx = xs - x0
            y1i = jnp.minimum(y0 + 1, H - 1)
            x1i = jnp.minimum(x0 + 1, W - 1)
            # channel map per bin: c = (cls*ch_per_cls + k)*G*G + gy*G + gx
            k = jnp.arange(ch_per_cls)
            cidx = (cls_id * ch_per_cls + k)[:, None, None] * (G * G) \
                + (gy[:, None] * G + gx[None, :])[None]        # (K, P, P)

            def gather(yi, xi):
                # channels (K,P,P); y (P,P,sp); x (P,P,sp) -> (K,P,P,sp,sp)
                return img[cidx[:, :, :, None, None],
                           yi[None, :, :, :, None],
                           xi[None, :, :, None, :]]
            wy_ = wy[None, :, :, :, None]
            wx_ = wx[None, :, :, None, :]
            v = (gather(y0, x0) * (1 - wy_) * (1 - wx_) +
                 gather(y0, x1i) * (1 - wy_) * wx_ +
                 gather(y1i, x0) * wy_ * (1 - wx_) +
                 gather(y1i, x1i) * wy_ * wx_)
            # v: (K, P, P, sp, sp) -> mean over samples
            return v.mean((-1, -2))

        outs = [one_class(c) for c in range(n_cls)]
        return jnp.concatenate(outs, 0)                         # (output_dim, P, P)

    if trans is None:
        return jax.vmap(lambda r: per_roi(r, None))(rois)
    return jax.vmap(per_roi)(rois, trans)


@register(name="_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, *, spatial_scale, output_dim, pooled_size,
                  group_size=0):
    """data (B, output_dim*G*G, H, W), rois (R,5) -> (R, output_dim, P, P)
    (reference psroi_pooling.cc; R-FCN head)."""
    return _psroi_impl(data, rois, None, spatial_scale=spatial_scale,
                       output_dim=output_dim, pooled_size=pooled_size,
                       group_size=group_size)


@register(name="_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",))
def deformable_psroi_pooling(data, rois, trans, *, spatial_scale, output_dim,
                             pooled_size, group_size, part_size=0,
                             sample_per_part=1, trans_std=0.0,
                             no_trans=False):
    """Deformable R-FCN pooling (reference deformable_psroi_pooling.cc):
    trans (R, 2*n_cls, part, part) shifts each bin by trans*roi_size."""
    return _psroi_impl(data, rois, None if no_trans else trans,
                       spatial_scale=spatial_scale, output_dim=output_dim,
                       pooled_size=pooled_size, group_size=group_size,
                       part_size=part_size, sample_per_part=sample_per_part,
                       trans_std=trans_std)


# ---------------------------------------------------------------------------
# Deformable convolution v1 (reference deformable_convolution.cc): bilinear
# sampling of the input at offset kernel-tap positions, then a dense
# contraction. The im2col+offset CUDA kernel becomes a static python loop
# over the kh*kw taps of gather-based bilinear samples — XLA fuses the taps;
# the contraction is one einsum on the MXU.
# ---------------------------------------------------------------------------

@register(name="_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, *, kernel,
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=0, num_group=1, num_deformable_group=1,
                           workspace=1024, no_bias=False, layout=None):
    from .spatial_ops import _bilinear_gather
    N, C, H, W = data.shape
    kh, kw = kernel
    sh, sw = stride if stride else (1, 1)
    dh, dw = dilate if dilate else (1, 1)
    ph, pw = pad if pad else (0, 0)
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = int(num_deformable_group)
    Cg = C // dg

    oy = jnp.arange(Ho, dtype=jnp.float32) * sh - ph
    ox = jnp.arange(Wo, dtype=jnp.float32) * sw - pw
    taps = []
    for ki in range(kh):
        for kj in range(kw):
            tap = ki * kw + kj
            per_dg = []
            for g in range(dg):
                off_y = offset[:, 2 * (g * kh * kw + tap)]        # (N,Ho,Wo)
                off_x = offset[:, 2 * (g * kh * kw + tap) + 1]
                gy = oy[None, :, None] + ki * dh + off_y
                gx = ox[None, None, :] + kj * dw + off_x
                sub = data[:, g * Cg:(g + 1) * Cg]
                per_dg.append(_bilinear_gather(sub, gx, gy))      # (N,Cg,Ho,Wo)
            taps.append(jnp.concatenate(per_dg, 1))               # (N,C,Ho,Wo)
    col = jnp.stack(taps, 2)                                      # (N,C,K,Ho,Wo)
    G = int(num_group)
    O = weight.shape[0]
    colg = col.reshape(N, G, C // G, kh * kw, Ho, Wo)
    wg = weight.reshape(G, O // G, C // G, kh * kw)
    out = jnp.einsum("ngckhw,gock->ngohw", colg, wg).reshape(N, O, Ho, Wo)
    if bias is not None and not no_bias:
        out = out + bias[None, :, None, None]
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# Misc contrib ops
# ---------------------------------------------------------------------------

@register(name="_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, *, out_dim, processing_batch_size=32):
    """Count-sketch projection (reference count_sketch.cc): out[:, h[i]] +=
    s[i] * data[:, i]. h, s: (1, in_dim)."""
    N, d = data.shape
    hh = jnp.clip(h.reshape(-1).astype(jnp.int32), 0, out_dim - 1)
    ss = s.reshape(-1).astype(data.dtype)
    out = jnp.zeros((N, int(out_dim)), data.dtype)
    return out.at[:, hh].add(data * ss[None, :])


@register(name="_contrib_fft", aliases=("fft",))
def fft(data, *, compute_size=128):
    """Real-to-complex FFT along the last axis; output interleaves re/im
    (reference fft.cc packs cuFFT output the same way): (..., d) -> (..., 2d)."""
    c = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([c.real, c.imag], -1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@register(name="_contrib_ifft", aliases=("ifft",))
def ifft(data, *, compute_size=128):
    """Inverse of _contrib_fft, UNNORMALIZED like cuFFT/the reference
    (ifft(fft(x)) == d * x): (..., 2d) -> (..., d) real part."""
    d = data.shape[-1] // 2
    pairs = data.astype(jnp.float32).reshape(data.shape[:-1] + (d, 2))
    c = lax.complex(pairs[..., 0], pairs[..., 1])
    return (jnp.fft.ifft(c, axis=-1).real * d).astype(data.dtype)


@register(name="_contrib_quadratic", aliases=("quadratic",))
def quadratic(data, *, a=0.0, b=0.0, c=0.0):
    """a*x^2 + b*x + c (reference quadratic_op.cc — the tutorial op)."""
    return a * data * data + b * data + c


@register(name="_contrib_gradientmultiplier",
          aliases=("gradientmultiplier", "GradientMultiplier"))
def gradient_multiplier(data, *, scalar=1.0):
    """Identity forward; backward scales the gradient by `scalar`
    (reference gradient_multiplier_op.cc — gradient-reversal layers use
    scalar=-lambda)."""
    sc = float(scalar)

    @jax.custom_vjp
    def _gm(x):
        return x

    _gm.defvjp(lambda x: (x, None), lambda _, g: (g * sc,))
    return _gm(data)


@register(name="_contrib_index_array", aliases=("index_array",), nondiff=True)
def index_array(data, *, axes=None):
    """Coordinate tensor: out[i1..in, k] = i_{axes[k]} (reference
    index_array.cc). Output dtype int64 in the reference; int32 here (XLA
    x64 is globally disabled)."""
    shape = data.shape
    nd_ = len(shape)
    sel = list(range(nd_)) if axes is None else [a % nd_ for a in axes]
    comps = [lax.broadcasted_iota(jnp.int32, shape, a) for a in sel]
    return jnp.stack(comps, -1)


@register(name="khatri_rao", aliases=("_contrib_khatri_rao",))
def khatri_rao(*matrices):
    """Column-wise Kronecker product (reference krprod.cc): inputs (n_i, k)
    -> (prod n_i, k)."""
    out = matrices[0]
    for m in matrices[1:]:
        k = out.shape[1]
        out = jnp.einsum("ik,jk->ijk", out, m).reshape(-1, k)
    return out


@register(name="_contrib_getnnz", aliases=("getnnz",), nondiff=True)
def getnnz(data, *, axis=None):
    """Number of stored/nonzero values (reference nnz.cc, defined for CSR).
    Dense inputs count exact nonzeros; axis=0/1 supported for 2-D."""
    nz = (data != 0).astype(jnp.int32)
    if axis is None:
        return jnp.sum(nz)
    return jnp.sum(nz, axis=int(axis))


@register(name="_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def div_sqrt_dim(data):
    """data / sqrt(d_last) (reference transformer.cc:33 — attention scaling)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


# ---------------------------------------------------------------------------
# Hawkes process log-likelihood (reference hawkes_ll.cc): exponential-kernel
# multivariate Hawkes, one lax.scan over the sequence replaces the per-sample
# C++ loop; gradients w.r.t. mu/alpha/beta come from autodiff instead of the
# reference's hand-written backward kernel.
# ---------------------------------------------------------------------------

@register(name="_contrib_hawkesll", aliases=("hawkesll",))
def hawkesll(mu, alpha, beta, state, lags, marks, valid_length, max_time):
    """mu (N,K), alpha (K,), beta (K,), state (N,K), lags (N,T),
    marks (N,T) int, valid_length (N,), max_time (N,) ->
    (loglik (N,), out_state (N,K))."""
    N, T = lags.shape
    K = mu.shape[1]
    marks_i = marks.astype(jnp.int32)

    def per_sample(mu_i, state_i, lag_i, mark_i, vl, mt):
        def step(carry, inp):
            ll, t, st, last = carry
            lag_j, m_j, j = inp
            t2 = t + lag_j
            oh = jax.nn.one_hot(m_j, K, dtype=mu_i.dtype)
            d = t2 - last
            ed = jnp.exp(-beta * d)
            lda = mu_i + alpha * beta * st * ed
            comp = mu_i * d + alpha * st * (1.0 - ed)
            contrib = jnp.sum(oh * (jnp.log(jnp.maximum(lda, 1e-30)) - comp))
            active = (j < vl).astype(mu_i.dtype)
            ll2 = ll + active * contrib
            st2 = jnp.where((oh > 0) & (j < vl), 1.0 + st * ed, st)
            last2 = jnp.where((oh > 0) & (j < vl), t2, last)
            t3 = jnp.where(j < vl, t2, t)
            return (ll2, t3, st2, last2), None

        init = (jnp.zeros((), mu_i.dtype), jnp.zeros((), mu_i.dtype),
                state_i, jnp.zeros((K,), mu_i.dtype))
        (ll, _, st, last), _ = lax.scan(
            step, init, (lag_i, mark_i, jnp.arange(T)))
        # remaining compensator to max_time + state decay (reference
        # hawkesll_forward_compensator)
        d = mt - last
        ed = jnp.exp(-beta * d)
        ll = ll - jnp.sum(mu_i * d + alpha * st * (1.0 - ed))
        return ll, st * ed

    return jax.vmap(per_sample)(mu, state, lags, marks_i, valid_length,
                                max_time)
