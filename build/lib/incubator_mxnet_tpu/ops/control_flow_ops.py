"""Control-flow operators lowering to XLA structured control flow.

Reference: src/operator/control_flow.cc — `_foreach` (:1089), `_while_loop`
(:1150), `_cond` (:1083) are stateful subgraph-holding ops with full
autograd (subgraph_op_common.cc).

TPU-native design: the subgraph is a Python callable traced by jax; the op
lowers to `lax.scan` / `lax.while_loop`-style constructs so the loop is NOT
unrolled in the XLA program (compile time independent of trip count) and
`jax.vjp` differentiates through it. The body here sees NDArray wrappers, so
user code written against the nd API runs unchanged inside the trace.

Closure semantics: arrays the body closes over (rather than receiving as
data/state inputs) are baked into the trace as constants — gradients flow
only to explicit inputs. The eager sugar in ndarray/contrib.py therefore
uses these ops only outside autograd recording, keeping the tape-recorded
unrolled loop when gradients through closures are needed (the reference's
imperative sugar is likewise an eager Python loop).
"""
from __future__ import annotations

from .registry import register

__all__ = []


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _wrap(datas):
    from ..ndarray import NDArray
    return [NDArray(d) for d in datas]


def _unwrap(arrs):
    from ..ndarray import NDArray
    return tuple(a._data if isinstance(a, NDArray) else a
                 for a in _as_list(arrs))


@register(name="_foreach")
def _foreach(*arrays, body, n_data, single_data, single_state):
    """lax.scan over axis 0 of the data arrays.

    Returns (out_0..out_k-1, final_state_0..final_state_m-1) flattened;
    the ndarray/contrib.py wrapper splits them (n_states = len(arrays) -
    n_data)."""
    from jax import lax

    data = tuple(arrays[:n_data])
    init = tuple(arrays[n_data:])

    def step(carry, xs):
        s = _wrap(carry)
        x = _wrap(xs)
        out, new_s = body(x[0] if single_data else x,
                          s[0] if single_state else s)
        return _unwrap(new_s), _unwrap(out)

    final, ys = lax.scan(step, init, data)
    return tuple(ys) + tuple(final)


@register(name="_while_loop")
def _while_loop(*arrays, cond, func, max_iterations):
    """Static-bound while: a scan of max_iterations steps where iterations
    past the loop exit are identity + zero outputs (matches the reference's
    zero-padded stacked outputs). Returns (steps, out_0.., var_0..)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    init = tuple(arrays)

    def run(vs):
        out, new_vs = func(*_wrap(vs))
        return _unwrap(new_vs), _unwrap(out) if out is not None else ()

    out_shapes = jax.eval_shape(lambda vs: run(vs)[1], init)

    def step(carry, _):
        vs, steps = carry
        pred = cond(*_wrap(vs))
        pred = pred._data.reshape(()).astype(bool) if hasattr(pred, "_data") \
            else jnp.asarray(pred).reshape(()).astype(bool)

        def do(v):
            return run(v)

        def skip(v):
            return v, tuple(jnp.zeros(s.shape, s.dtype) for s in out_shapes)

        new_vs, out_t = lax.cond(pred, do, skip, vs)
        return (new_vs, steps + pred.astype(jnp.int32)), out_t

    (final_vs, steps), ys = lax.scan(
        step, (init, jnp.zeros((), jnp.int32)), None, length=max_iterations)
    return (steps,) + tuple(ys) + tuple(final_vs)


@register(name="_cond")
def _cond(pred, *arrays, then_func, else_func, n_then):
    """lax.cond over two traced branches; `arrays` are the explicit branch
    inputs (first n_then feed then_func, the rest else_func)."""
    from jax import lax
    import jax.numpy as jnp

    p = pred.reshape(()).astype(bool)
    t_in = tuple(arrays[:n_then])
    e_in = tuple(arrays[n_then:])

    def t(ops):
        ti, ei = ops
        return _unwrap(then_func(*_wrap(ti)))

    def e(ops):
        ti, ei = ops
        return _unwrap(else_func(*_wrap(ei)))

    out = lax.cond(p, t, e, (t_in, e_in))
    return out if len(out) > 1 else out[0]
