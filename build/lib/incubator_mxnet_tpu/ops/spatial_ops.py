"""Spatial-transform op family.

Reference: src/operator/spatial_transformer-inl.h, grid_generator-inl.h,
bilinear_sampler-inl.h (cuDNN paths cudnn_spatial_transformer-inl.h,
cudnn_bilinear_sampler), src/operator/correlation-inl.h (FlowNet
correlation layer), src/operator/svm_output-inl.h.

TPU-native design: all samplers are gather-based (vectorized advanced
indexing lowers to XLA gather, which tiles fine) with the out-of-bounds
zero-padding expressed as masked accumulation — no scalar loops, fully
differentiable through jax autodiff, so no hand-written backward kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _bilinear_gather(data, gx, gy):
    """Sample data (N,C,H,W) at pixel coords gx/gy (N,Ho,Wo) with bilinear
    interpolation and zero padding outside the image."""
    N, C, H, W = data.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    out = 0.0
    bidx = jnp.arange(N)[:, None, None]
    for dy in (0, 1):
        for dx in (0, 1):
            xs = x0 + dx
            ys = y0 + dy
            w = (1 - jnp.abs(gx - xs)) * (1 - jnp.abs(gy - ys))
            valid = (xs >= 0) & (xs <= W - 1) & (ys >= 0) & (ys <= H - 1)
            xc = jnp.clip(xs, 0, W - 1).astype(jnp.int32)
            yc = jnp.clip(ys, 0, H - 1).astype(jnp.int32)
            v = data[bidx, :, yc, xc]                 # (N, Ho, Wo, C)
            out = out + v * (w * valid)[..., None].astype(data.dtype)
    return jnp.moveaxis(out, -1, 1)                   # (N, C, Ho, Wo)


@register(name="BilinearSampler", aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid, *, cudnn_off=None):
    """data (N,C,H,W); grid (N,2,Ho,Wo) with grid[:,0]=x, grid[:,1]=y in
    [-1,1] (reference bilinear_sampler-inl.h: -1 maps to pixel 0, +1 to
    W-1/H-1; outside is zero-padded)."""
    N, C, H, W = data.shape
    gx = (grid[:, 0] + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return _bilinear_gather(data, gx, gy)


@register(name="GridGenerator", aliases=("grid_generator",))
def grid_generator(data, *, transform_type="affine", target_shape=(0, 0)):
    """affine: data (N,6) row-major 2x3 theta -> normalized sampling grid
    (N,2,H,W). warp: data (N,2,H,W) is a pixel-unit optical flow added to
    the identity grid, renormalized to [-1,1]."""
    if transform_type == "affine":
        N = data.shape[0]
        H, W = int(target_shape[0]), int(target_shape[1])
        theta = jnp.reshape(data, (N, 2, 3)).astype(jnp.float32)
        ys, xs = jnp.meshgrid(jnp.linspace(-1.0, 1.0, H),
                              jnp.linspace(-1.0, 1.0, W), indexing="ij")
        ones = jnp.ones_like(xs)
        src = jnp.stack([xs, ys, ones], 0).reshape(3, -1)   # (3, H*W)
        out = jnp.einsum("nij,jk->nik", theta, src)          # (N, 2, H*W)
        return out.reshape(N, 2, H, W).astype(data.dtype)
    if transform_type == "warp":
        N, _, H, W = data.shape
        ys, xs = jnp.meshgrid(jnp.arange(H, dtype=jnp.float32),
                              jnp.arange(W, dtype=jnp.float32), indexing="ij")
        gx = (data[:, 0] + xs) * 2.0 / max(W - 1, 1) - 1.0
        gy = (data[:, 1] + ys) * 2.0 / max(H - 1, 1) - 1.0
        return jnp.stack([gx, gy], 1).astype(data.dtype)
    raise ValueError(f"GridGenerator transform_type {transform_type!r}")


@register(name="SpatialTransformer", aliases=("spatial_transformer",))
def spatial_transformer(data, loc, *, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):
    """STN (Jaderberg et al.): affine grid from loc (N,6), bilinear sample
    (reference spatial_transformer-inl.h composes the same two stages)."""
    grid = grid_generator.fn(loc, transform_type=transform_type,
                             target_shape=tuple(target_shape))
    return bilinear_sampler.fn(data, grid)


@register(name="Correlation", aliases=("correlation",))
def correlation(data1, data2, *, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation (reference correlation-inl.h): for every output
    position, correlate a kernel_size^2 patch of data1 with displaced
    patches of data2 over a (2*max_displacement/stride2+1)^2 grid.

    The displacement grid is a static python loop (D^2 shifted elementwise
    products — XLA fuses them); patch aggregation is an average pool.
    Output: (N, D*D, Ho, Wo), normalized by patch volume like the
    reference (sumelems = kernel^2 * C).
    """
    N, C, H, W = data1.shape
    d = int(max_displacement)
    pad = int(pad_size)
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    k = int(kernel_size)
    kr = k // 2
    bord = d + kr
    import math
    Hp, Wp = H + 2 * pad, W + 2 * pad
    Ho = int(math.ceil((Hp - bord * 2) / float(stride1)))
    Wo = int(math.ceil((Wp - bord * 2) / float(stride1)))

    maps = []
    for dy in range(-(d // stride2), d // stride2 + 1):
        for dx in range(-(d // stride2), d // stride2 + 1):
            sy, sx = dy * stride2, dx * stride2
            shifted = jnp.roll(p2, (-sy, -sx), axis=(2, 3))
            # reference accumulates fabsf(a-b) (no negation) for the
            # subtract variant
            prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
            m = jnp.mean(prod, axis=1)                  # (N, Hp, Wp) mean over C
            if k > 1:
                m = lax.reduce_window(m, 0.0, lax.add, (1, k, k), (1, 1, 1),
                                      "SAME") / (k * k)
            maps.append(m)
    corr = jnp.stack(maps, axis=1)                      # (N, D*D, Hp, Wp)
    # valid output window: centers where the full displaced patch exists
    corr = corr[:, :, bord:bord + Ho * stride1:stride1,
                bord:bord + Wo * stride1:stride1]
    return corr.astype(data1.dtype)


@register(name="SVMOutput", aliases=("svm_output",))
def svm_output(data, label, *, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Forward is identity (scores pass through, like SoftmaxOutput);
    the one-vs-all hinge loss shapes the BACKWARD. Expressed as a
    straight-through custom-vjp. Matches the reference's L1_SVM gradient
    (src/operator/svm_output.cc:31-48); for L2 the reference's L2_SVM
    (:50-64) emits the opposite sign from its own L1 (and drops reg) —
    here both use the consistent descent direction
    d = -reg * sign * dviol (L1) / -2*reg * sign * viol (L2)."""
    m = float(margin)
    reg = float(regularization_coefficient)

    @jax.custom_vjp
    def _svm(scores, lab):
        return scores

    def fwd(scores, lab):
        return scores, (scores, lab)

    def bwd(res, g):
        scores, lab = res
        n, k = scores.shape
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), k,
                                dtype=scores.dtype)
        sign = 2.0 * onehot - 1.0              # +1 at true class, -1 else
        viol = jnp.maximum(0.0, m - sign * scores)
        if use_linear:
            grad = -reg * sign * (viol > 0)
        else:
            grad = -2.0 * reg * sign * viol
        # like the reference loss layers, the incoming head grad is ignored
        if jnp.issubdtype(lab.dtype, jnp.floating):
            zlab = jnp.zeros_like(lab)
        else:
            import numpy as _np
            from jax import dtypes as _dtypes
            zlab = _np.zeros(lab.shape, _dtypes.float0)
        return (grad.astype(scores.dtype), zlab)

    _svm.defvjp(fwd, bwd)
    return _svm(data, label)
