"""Random samplers (reference src/operator/random/: sample_op.cc, multisample,
shuffle.cc; per-device RNG resource include/mxnet/random_generator.h).

TPU-native redesign: the reference keeps mutable per-device Philox states
handed out by the ResourceManager; here every sampler is a pure function of a
jax PRNG key. The framework-level key chain lives in ndarray/random.py
(split-per-call), which is the functional equivalent of the reference's
per-device stateful generators and is what makes samplers safe under jit and
across a device mesh.
"""
from __future__ import annotations

from ..base import dtype_np
from .registry import register

import jax
import jax.numpy as jnp


@register(name="_random_uniform", aliases=("uniform",), stateful=True, nondiff=True)
def _random_uniform(*, low=0.0, high=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.uniform(rng, tuple(shape), dtype_np(dtype), low, high)


@register(name="_random_normal", aliases=("normal",), stateful=True, nondiff=True)
def _random_normal(*, loc=0.0, scale=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.normal(rng, tuple(shape), dtype_np(dtype)) * scale + loc


@register(name="_random_gamma", stateful=True, nondiff=True)
def _random_gamma(*, alpha=1.0, beta=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.gamma(rng, alpha, tuple(shape), dtype_np(dtype)) * beta


@register(name="_random_exponential", stateful=True, nondiff=True)
def _random_exponential(*, lam=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.exponential(rng, tuple(shape), dtype_np(dtype)) / lam


@register(name="_random_poisson", stateful=True, nondiff=True)
def _random_poisson(*, lam=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.poisson(rng, lam, tuple(shape)).astype(dtype_np(dtype))


@register(name="_random_negative_binomial", stateful=True, nondiff=True)
def _random_negative_binomial(*, k=1, p=1.0, shape=(1,), dtype="float32", rng=None):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(dtype_np(dtype))


@register(name="_random_generalized_negative_binomial", stateful=True, nondiff=True)
def _random_gnb(*, mu=1.0, alpha=1.0, shape=(1,), dtype="float32", rng=None):
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(dtype_np(dtype))


@register(name="_random_randint", stateful=True, nondiff=True)
def _random_randint(*, low=0, high=1, shape=(1,), dtype="int32", rng=None):
    return jax.random.randint(rng, tuple(shape), low, high, dtype_np(dtype))


@register(name="_sample_multinomial", stateful=True, nondiff=True)
def _sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32", rng=None):
    """data: (..., K) probabilities; draw `shape` samples per distribution
    (reference src/operator/random/sample_multinomial_op.cc)."""
    n = 1
    for s in (shape if isinstance(shape, (tuple, list)) else (shape,)):
        n *= max(int(s), 1)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    out_shape = data.shape[:-1] + ((n,) if shape else ())
    draws = jax.random.categorical(rng, logits, axis=-1,
                                   shape=(n,) + data.shape[:-1])
    if data.ndim == 1:
        samp = draws if shape else draws[0]
    else:
        samp = jnp.moveaxis(draws, 0, -1)
        if not shape:
            samp = samp[..., 0]
    samp = samp.astype(dtype_np(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jnp.log(jnp.maximum(data, 1e-37)),
            samp.astype(jnp.int32).reshape(data.shape[:-1] + (-1,)), axis=-1)
        return (samp, lp.reshape(samp.shape))
    return samp


@register(name="_shuffle", stateful=True, nondiff=True)
def _shuffle(data, *, rng=None):
    """Shuffle along first axis (reference src/operator/random/shuffle_op.cc)."""
    perm = jax.random.permutation(rng, data.shape[0])
    return data[perm]


# ---------------------------------------------------------------------------
# Tensor-parameter samplers (reference src/operator/random/multisample_op.cc:
# each row of the parameter arrays parameterizes one distribution; `shape`
# draws that many samples per distribution, output = param_shape + shape).
# ---------------------------------------------------------------------------

def _multisample(draw, params, shape, dtype, rng):
    shape = tuple(shape) if isinstance(shape, (tuple, list)) else \
        ((int(shape),) if shape else ())
    pshape = jnp.broadcast_shapes(*[jnp.shape(p) for p in params])
    bparams = [jnp.broadcast_to(p, pshape) for p in params]
    # draw over trailing sample axes with params broadcast against them
    exp = [p.reshape(pshape + (1,) * len(shape)) for p in bparams]
    out = draw(rng, exp, pshape + shape)
    return out.astype(dtype_np(dtype))


@register(name="_sample_uniform", aliases=("sample_uniform",), stateful=True,
          nondiff=True)
def _sample_uniform(low, high, *, shape=(), dtype="float32", rng=None):
    return _multisample(
        lambda k, p, s: jax.random.uniform(k, s) * (p[1] - p[0]) + p[0],
        [low, high], shape, dtype, rng)


@register(name="_sample_normal", aliases=("sample_normal",), stateful=True,
          nondiff=True)
def _sample_normal(mu, sigma, *, shape=(), dtype="float32", rng=None):
    return _multisample(
        lambda k, p, s: jax.random.normal(k, s) * p[1] + p[0],
        [mu, sigma], shape, dtype, rng)


@register(name="_sample_gamma", aliases=("sample_gamma",), stateful=True,
          nondiff=True)
def _sample_gamma(alpha, beta, *, shape=(), dtype="float32", rng=None):
    return _multisample(
        lambda k, p, s: jax.random.gamma(k, jnp.broadcast_to(p[0], s)) * p[1],
        [alpha, beta], shape, dtype, rng)


@register(name="_sample_exponential", aliases=("sample_exponential",),
          stateful=True, nondiff=True)
def _sample_exponential(lam, *, shape=(), dtype="float32", rng=None):
    return _multisample(
        lambda k, p, s: jax.random.exponential(k, s) / p[0],
        [lam], shape, dtype, rng)


@register(name="_sample_poisson", aliases=("sample_poisson",), stateful=True,
          nondiff=True)
def _sample_poisson(lam, *, shape=(), dtype="float32", rng=None):
    return _multisample(
        lambda k, p, s: jax.random.poisson(k, jnp.broadcast_to(p[0], s), s),
        [lam], shape, dtype, rng)


@register(name="_sample_negative_binomial", aliases=("sample_negative_binomial",),
          stateful=True, nondiff=True)
def _sample_negative_binomial(k, p, *, shape=(), dtype="float32", rng=None):
    def draw(key, prm, s):
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, jnp.broadcast_to(prm[0], s)) \
            * (1 - prm[1]) / prm[1]
        return jax.random.poisson(k2, lam, s)
    return _multisample(draw, [k, p], shape, dtype, rng)


@register(name="_sample_generalized_negative_binomial",
          aliases=("sample_generalized_negative_binomial",), stateful=True,
          nondiff=True)
def _sample_gnb(mu, alpha, *, shape=(), dtype="float32", rng=None):
    def draw(key, prm, s):
        k1, k2 = jax.random.split(key)
        r = 1.0 / jnp.maximum(prm[1], 1e-12)
        pp = r / (r + prm[0])
        lam = jax.random.gamma(k1, jnp.broadcast_to(r, s)) * (1 - pp) / pp
        return jax.random.poisson(k2, lam, s)
    return _multisample(draw, [mu, alpha], shape, dtype, rng)


# ---------------------------------------------------------------------------
# Probability-density ops (reference src/operator/random/pdf_op.cc — ~2,000
# LoC of hand-written pdf + gradient kernels). Here each pdf is plain jnp
# math, so forward AND gradients (w.r.t. both samples and distribution
# parameters) come from jax autodiff; the sample axis convention matches the
# reference: params of shape s, samples of shape s + (n,), output s + (n,).
# ---------------------------------------------------------------------------

def _pdf_wrap(logpdf_fn, sample, params, is_log):
    exp = [jnp.asarray(p)[..., None] for p in params]
    lp = logpdf_fn(sample, exp)
    return lp if is_log else jnp.exp(lp)


@register(name="_random_pdf_uniform", aliases=("random_pdf_uniform",))
def _random_pdf_uniform(sample, low, high, *, is_log=False):
    def lp(x, p):
        low_, high_ = p
        inside = (x >= low_) & (x <= high_)
        return jnp.where(inside, -jnp.log(high_ - low_), -jnp.inf)
    return _pdf_wrap(lp, sample, [low, high], is_log)


@register(name="_random_pdf_normal", aliases=("random_pdf_normal",))
def _random_pdf_normal(sample, mu, sigma, *, is_log=False):
    def lp(x, p):
        mu_, sg = p
        z = (x - mu_) / sg
        return -0.5 * z * z - jnp.log(sg) - 0.5 * jnp.log(2 * jnp.pi)
    return _pdf_wrap(lp, sample, [mu, sigma], is_log)


@register(name="_random_pdf_gamma", aliases=("random_pdf_gamma",))
def _random_pdf_gamma(sample, alpha, beta, *, is_log=False):
    from jax.scipy.special import gammaln

    def lp(x, p):
        a, b = p
        # beta is a RATE here (lpdf = a*log(b) + (a-1)*log(x) - b*x), matching
        # the reference pdf kernel even though its SAMPLER uses beta as a
        # scale — the inconsistency is the reference's own, kept for parity.
        return a * jnp.log(b) + (a - 1) * jnp.log(x) - b * x - gammaln(a)
    return _pdf_wrap(lp, sample, [alpha, beta], is_log)


@register(name="_random_pdf_exponential", aliases=("random_pdf_exponential",))
def _random_pdf_exponential(sample, lam, *, is_log=False):
    def lp(x, p):
        return jnp.log(p[0]) - p[0] * x
    return _pdf_wrap(lp, sample, [lam], is_log)


@register(name="_random_pdf_poisson", aliases=("random_pdf_poisson",))
def _random_pdf_poisson(sample, lam, *, is_log=False):
    from jax.scipy.special import gammaln

    def lp(x, p):
        return x * jnp.log(p[0]) - p[0] - gammaln(x + 1.0)
    return _pdf_wrap(lp, sample, [lam], is_log)


@register(name="_random_pdf_negative_binomial",
          aliases=("random_pdf_negative_binomial",))
def _random_pdf_negative_binomial(sample, k, p, *, is_log=False):
    from jax.scipy.special import gammaln

    def lp(x, prm):
        k_, p_ = prm
        return (gammaln(x + k_) - gammaln(x + 1.0) - gammaln(k_)
                + k_ * jnp.log(p_) + x * jnp.log1p(-p_))
    return _pdf_wrap(lp, sample, [k, p], is_log)


@register(name="_random_pdf_generalized_negative_binomial",
          aliases=("random_pdf_generalized_negative_binomial",))
def _random_pdf_gnb(sample, mu, alpha, *, is_log=False):
    from jax.scipy.special import gammaln

    def lp(x, prm):
        mu_, a = prm
        r = 1.0 / a
        p_ = r / (r + mu_)
        return (gammaln(x + r) - gammaln(x + 1.0) - gammaln(r)
                + r * jnp.log(p_) + x * jnp.log1p(-p_))
    return _pdf_wrap(lp, sample, [mu, alpha], is_log)


@register(name="_random_pdf_dirichlet", aliases=("random_pdf_dirichlet",))
def _random_pdf_dirichlet(sample, alpha, *, is_log=False):
    """alpha: (..., K); sample: (..., n, K) simplex points; out: (..., n)."""
    from jax.scipy.special import gammaln
    a = jnp.asarray(alpha)[..., None, :]
    lp = (jnp.sum((a - 1) * jnp.log(sample), axis=-1)
          + gammaln(jnp.sum(a, axis=-1)) - jnp.sum(gammaln(a), axis=-1))
    return lp if is_log else jnp.exp(lp)


@register(name="_sample_unique_zipfian", stateful=True, nondiff=True)
def _sample_unique_zipfian(*, range_max, shape=(1,), rng=None):
    u = jax.random.uniform(rng, tuple(shape))
    out = (jnp.exp(u * jnp.log(range_max + 1.0)) - 1.0).astype(jnp.int32)
    return jnp.clip(out, 0, range_max - 1)
