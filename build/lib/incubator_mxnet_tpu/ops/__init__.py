"""Operator library (TPU-native re-design of src/operator/, see SURVEY.md §2.2).

Submodules register ops into `registry.OPS`; the `nd` and `sym` namespaces
expose them. Import order matters only in that registration must happen before
namespace lookup — handled by ndarray/__init__.py.
"""
from . import registry
from .registry import OPS, OpDef, apply_op, get_op, invoke, register

__all__ = ["registry", "OPS", "OpDef", "apply_op", "get_op", "invoke", "register"]
