"""Failure handling: checkpoint/resume + preemption (SURVEY §5.3).

The reference's failure story is thin — ps-lite node timeouts surface as
`kv.get_dead_nodes(timeout)` (src/kvstore/kvstore_dist.h:121) and a
restart-recovery flag skips the startup barrier; there is no automatic
checkpoint-resume orchestration. On TPU pods preemption is routine, so
this module goes further:

- ``CheckpointManager``: atomic (write-tmp + rename), rotating, resumable
  checkpoints of net parameters + trainer state, with a manifest that
  survives partial writes.
- ``PreemptionHandler``: SIGTERM/SIGINT hook that flips a flag (and
  optionally checkpoints immediately) so training loops can exit cleanly
  at the next step boundary.
- ``get_dead_nodes``: liveness parity API (reference kvstore_dist.h:121);
  under the single-controller jax runtime a missing host fails the whole
  program, so live == all.
"""
from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
import time

from .base import MXNetError

__all__ = ["CheckpointManager", "PreemptionHandler", "get_dead_nodes",
           "resume_or_start"]


class CheckpointManager:
    """Atomic rotating checkpoints for (net, trainer).

    Layout: ``{dir}/{prefix}-{step:08d}.params`` (+ ``.states`` when a
    trainer is given) and a ``{prefix}.manifest.json`` that is only
    updated AFTER the artifact files are fully on disk — a crash mid-save
    never corrupts the latest restorable step.
    """

    def __init__(self, directory, prefix="ckpt", max_keep=3):
        self.directory = directory
        self.prefix = prefix
        self.max_keep = max_keep
        os.makedirs(directory, exist_ok=True)

    # -- paths ---------------------------------------------------------
    def _manifest_path(self):
        return os.path.join(self.directory, f"{self.prefix}.manifest.json")

    def _params_path(self, step):
        return os.path.join(self.directory,
                            f"{self.prefix}-{step:08d}.params")

    def _states_path(self, step):
        return os.path.join(self.directory,
                            f"{self.prefix}-{step:08d}.states")

    def _read_manifest(self):
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"steps": []}

    def _write_atomic(self, path, writer):
        fd, tmp = tempfile.mkstemp(dir=self.directory,
                                   prefix=os.path.basename(path) + ".tmp")
        os.close(fd)
        try:
            writer(tmp)
            # flush DATA before the rename: a journaled rename without a
            # data fsync can survive power loss pointing at torn content
            fd2 = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd2)
            finally:
                os.close(fd2)
            os.replace(tmp, path)  # atomic on POSIX
            dirfd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    # -- API -----------------------------------------------------------
    def save(self, step, net, trainer=None, extra=None):
        """Checkpoint at `step`. Returns the params path."""
        step = int(step)
        ppath = self._params_path(step)
        self._write_atomic(ppath, net.save_parameters)
        if trainer is not None:
            self._write_atomic(self._states_path(step), trainer.save_states)
        man = self._read_manifest()
        entry = {"step": step, "has_states": trainer is not None,
                 "time": time.time()}
        if extra:
            entry["extra"] = extra
        man["steps"] = [e for e in man["steps"] if e["step"] != step]
        man["steps"].append(entry)
        man["steps"].sort(key=lambda e: e["step"])
        while len(man["steps"]) > self.max_keep:
            old = man["steps"].pop(0)
            for p in (self._params_path(old["step"]),
                      self._states_path(old["step"])):
                if os.path.exists(p):
                    os.remove(p)
        def write_manifest(tmp):
            with open(tmp, "w") as f:
                f.write(json.dumps(man, indent=1))

        self._write_atomic(self._manifest_path(), write_manifest)
        return ppath

    def latest_step(self):
        """Newest restorable step, or None."""
        for e in reversed(self._read_manifest()["steps"]):
            if os.path.exists(self._params_path(e["step"])):
                return e["step"]
        return None

    def restore(self, net, trainer=None, step=None, ctx=None):
        """Load params (+trainer states) from `step` (default: latest).
        Returns the restored step number. Raises if the manifest says the
        step was saved WITH trainer state but the .states file is gone —
        silently resetting optimizer state is not a resume."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise MXNetError(f"no checkpoint found in {self.directory}")
        net.load_parameters(self._params_path(step), ctx=ctx)
        if trainer is not None:
            spath = self._states_path(step)
            expected = any(e["step"] == step and e.get("has_states")
                           for e in self._read_manifest()["steps"])
            if os.path.exists(spath):
                trainer.load_states(spath)
            elif expected:
                raise MXNetError(
                    f"checkpoint step {step} was saved with trainer state "
                    f"but {spath} is missing; refusing a silent partial "
                    "resume (pass trainer=None to load params only)")
        return step

    def extra(self, step=None):
        """The `extra` dict saved with a step (default: latest)."""
        if step is None:
            step = self.latest_step()
        for e in self._read_manifest()["steps"]:
            if e["step"] == step:
                return e.get("extra", {})
        return {}


def resume_or_start(manager, net, trainer=None, ctx=None):
    """Restore the latest checkpoint if one exists; returns the step to
    resume from (0 when starting fresh)."""
    step = manager.latest_step()
    if step is None:
        return 0
    manager.restore(net, trainer, step=step, ctx=ctx)
    return step


class PreemptionHandler:
    """SIGTERM/SIGINT-driven graceful stop.

    The signal handler ONLY sets a flag — checkpointing from inside a
    signal handler could capture parameters mid-update. `on_preempt` is
    deferred to the first `should_stop()` call after the signal, i.e. the
    training loop's step boundary, where state is consistent.

    usage:
        with PreemptionHandler() as pre:
            for step in range(start, total):
                ...train one step...
                if pre.should_stop():
                    mgr.save(step, net, trainer)
                    break
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_preempt=None):
        self._signals = tuple(signals)
        self._on_preempt = on_preempt
        self._stop = threading.Event()
        self._callback_fired = False
        self._prev = {}
        self._installed = False

    def _handler(self, signum, frame):
        self._stop.set()

    def install(self):
        if self._installed:
            return self
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        self._installed = True
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def should_stop(self):
        stopped = self._stop.is_set()
        if stopped and self._on_preempt is not None and \
                not self._callback_fired:
            # deferred to here: main-thread, step-boundary context
            self._callback_fired = True
            try:
                self._on_preempt()
            except Exception:
                pass  # never mask the shutdown path
        return stopped

    def reset(self):
        self._stop.clear()
        self._callback_fired = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()


def get_dead_nodes(timeout_sec=60):
    """Liveness parity API (reference kvstore_dist.h:121 get_dead_nodes).

    Under jax's single-controller runtime a dead host aborts the program
    (there is no partial-failure mode to report), so any process that can
    call this sees every peer alive: returns [].
    """
    return []
