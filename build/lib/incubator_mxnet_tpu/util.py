"""Misc utilities (reference python/mxnet/util.py, 604 LoC).

The reference's util.py mostly manages numpy-shape/array semantics switches
threaded through the C API; here those are process-local flags consumed by
the mxnet.numpy namespace, plus the small filesystem/env helpers user code
imports.
"""
from __future__ import annotations

import functools
import os
import threading

__all__ = ["makedirs", "set_np_shape", "is_np_shape", "use_np_shape",
           "np_shape", "set_np_array", "is_np_array", "np_array", "use_np",
           "set_np", "reset_np", "getenv", "setenv", "default_array"]

_tls = threading.local()


def makedirs(d):
    """Reference util.py makedirs (py2 compat wrapper there; kept for API)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


# -- numpy-semantics switches (reference util.py set_np_shape:68 etc.) ------

def _flags():
    if not hasattr(_tls, "np_shape"):
        _tls.np_shape = False
        _tls.np_array = False
    return _tls


def set_np_shape(active):
    """Allow zero-dim/zero-size arrays (reference util.py:68). Under jax
    these are always expressible; the flag only controls legacy-shape
    validation in the NDArray layer."""
    prev = _flags().np_shape
    _flags().np_shape = bool(active)
    return prev


def is_np_shape():
    return _flags().np_shape


def set_np_array(active):
    prev = _flags().np_array
    _flags().np_array = bool(active)
    return prev


def is_np_array():
    return _flags().np_array


class _NpShapeScope:
    def __init__(self, shape=True, array=None):
        self._shape = shape
        self._array = array

    def __enter__(self):
        self._prev_shape = set_np_shape(self._shape)
        if self._array is not None:
            self._prev_array = set_np_array(self._array)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev_shape)
        if self._array is not None:
            set_np_array(self._prev_array)


def np_shape(active=True):
    """Context manager (reference util.py np_shape)."""
    return _NpShapeScope(shape=active)


def np_array(active=True):
    return _NpShapeScope(shape=is_np_shape(), array=active)


def use_np_shape(func):
    """Decorator (reference util.py use_np_shape)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)

    return wrapper


def use_np(func):
    """Decorator enabling both np shape + array semantics
    (reference util.py use_np)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with _NpShapeScope(shape=True, array=True):
            return func(*args, **kwargs)

    return wrapper


def set_np(shape=True, array=True):
    set_np_shape(shape)
    set_np_array(array)


def reset_np():
    set_np(False, False)


def getenv(name):
    """Reference util.py getenv -> MXGetEnv."""
    return os.environ.get(name)


def setenv(name, value):
    os.environ[name] = value


def default_array(source_array, ctx=None, dtype=None):
    """Array in the currently-active frontend semantics (reference
    util.py default_array)."""
    if is_np_array():
        from . import numpy as np_mod
        return np_mod.array(source_array, dtype=dtype)
    from . import nd
    return nd.array(source_array, dtype=dtype)
