"""Runtime-compiled custom kernels.

Reference: python/mxnet/rtc.py (CudaModule over NVRTC, src/common/rtc.cc:
35-61 — compile CUDA C at runtime, fetch kernels by name, launch on a
ctx with grid/block dims). TPU-native redesign: the runtime kernel
compiler for TPU is **Pallas/Mosaic** — a kernel is a Python function over
`pl.Ref`s, compiled at `launch` time for the current backend. The module
keeps CudaModule's shape (module -> get_kernel -> launch) so user code
ports structurally, but grids/blocks become Pallas grid + BlockSpecs.

    src = '''
    def scale_add(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + y_ref[...]
    '''
    mod = mx.rtc.PallasModule(src, exports=["scale_add"])
    k = mod.get_kernel("scale_add", out_like=x)
    out = k.launch([x, y])

On non-TPU backends kernels run through the Pallas interpreter, so the
same source is testable on the CPU mesh.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["PallasModule", "Kernel", "CudaModule"]


class Kernel:
    """One launchable kernel (reference rtc.py Kernel.launch)."""

    def __init__(self, fn, name, out_shapes, out_dtypes, grid=None,
                 in_specs=None, out_specs=None):
        self._fn = fn
        self._name = name
        self._out_shapes = out_shapes
        self._out_dtypes = out_dtypes
        self._grid = grid
        self._in_specs = in_specs
        self._out_specs = out_specs
        self._compiled = {}       # keyed by effective grid

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Run the kernel. grid_dims maps onto the Pallas grid (block_dims/
        shared_mem have no TPU analog — Mosaic owns tiling — and are
        accepted but ignored for signature parity)."""
        import jax
        import jax.numpy as jnp

        arrs = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                for a in args]
        grid = tuple(grid_dims) if grid_dims is not None else \
            (tuple(self._grid) if self._grid is not None else None)
        fn = self._compiled.get(grid)
        if fn is None:
            from jax.experimental import pallas as pl

            out_shape = [jax.ShapeDtypeStruct(s, d) for s, d in
                         zip(self._out_shapes, self._out_dtypes)]
            single = len(out_shape) == 1
            kwargs = {}
            if grid is not None:
                kwargs["grid"] = grid
            if self._in_specs is not None:
                kwargs["in_specs"] = self._in_specs
            if self._out_specs is not None:
                kwargs["out_specs"] = self._out_specs if not single \
                    else self._out_specs[0]
            interpret = jax.default_backend() != "tpu"
            call = pl.pallas_call(
                self._fn, out_shape=out_shape[0] if single else out_shape,
                interpret=interpret, **kwargs)
            fn = jax.jit(call)
            self._compiled[grid] = fn
        out = fn(*arrs)
        if isinstance(out, (list, tuple)):
            return [NDArray(o) for o in out]
        return NDArray(out)


class PallasModule:
    """Compile-at-runtime kernel module (reference rtc.py CudaModule).

    source: python source text defining kernel functions over pallas Refs
    (exec'd with `pl`, `jnp`, `jax` in scope), or None to register python
    callables directly via get_kernel(fn, ...).
    """

    def __init__(self, source=None, options=(), exports=()):
        self._ns = {}
        self.exports = tuple(exports)
        if source is not None:
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            # ONE namespace as both globals and locals, so kernels can call
            # helper functions / constants defined in the same source
            self._ns.update({"pl": pl, "jnp": jnp, "jax": jax})
            exec(compile(source, "<rtc>", "exec"), self._ns)
            missing = [e for e in self.exports if e not in self._ns]
            if missing:
                raise MXNetError(f"exported kernels not defined: {missing}")

    def get_kernel(self, name, signature=None, *, out_like=None,
                   out_shapes=None, out_dtypes=None, grid=None,
                   in_specs=None, out_specs=None):
        """Fetch a kernel by name (or pass a callable). Output shapes come
        from `out_like` (an example array) or explicit out_shapes/
        out_dtypes; `signature` is accepted for reference-API parity but
        unused (Pallas kernels are typed by their Refs)."""
        fn = name if callable(name) else self._ns.get(name)
        if fn is None:
            raise MXNetError(f"kernel {name!r} not found in module")
        if out_like is not None:
            ol = out_like._data if isinstance(out_like, NDArray) else out_like
            out_shapes = [ol.shape]
            out_dtypes = [ol.dtype]
        if out_shapes is None or out_dtypes is None:
            raise MXNetError("get_kernel needs out_like or "
                             "out_shapes+out_dtypes")
        return Kernel(fn, getattr(fn, "__name__", str(name)), out_shapes,
                      out_dtypes, grid=grid, in_specs=in_specs,
                      out_specs=out_specs)


def CudaModule(*a, **kw):
    """CUDA RTC has no TPU analog — point users at PallasModule."""
    raise MXNetError("CudaModule is CUDA-specific; use rtc.PallasModule "
                     "(runtime-compiled Pallas/Mosaic kernels) on TPU")
