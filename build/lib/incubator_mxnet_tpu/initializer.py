"""Weight initializers (reference: python/mxnet/initializer.py, 758 LoC:
Uniform/Normal/Xavier/MSRAPrelu/Orthogonal/Bilinear/LSTMBias/Constant/Mixed).
"""
from __future__ import annotations

import math
import re

import numpy as _np

from .base import MXNetError, Registry
from . import nd

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "Mixed",
           "register", "create"]

_REG = Registry("initializer")
register = _REG.register


class InitDesc(str):
    """Parameter name + attrs hint passed to initializers
    (reference initializer.py:38)."""
    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer; __call__(name, arr) fills arr in place."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        if not isinstance(name, InitDesc):
            name = InitDesc(name)
        init = name.attrs.get("__init__", "")
        if init:
            create(init)._init_weight(name, arr)
            return
        if name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(name, arr)
        else:
            self._init_default(name, arr)

    def init_weight(self, name, arr):
        self._init_weight(InitDesc(name), arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    """alias: zeros"""
    def _init_weight(self, name, arr):
        arr[:] = 0.0


_REG.register(Zero, name="zeros")


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


_REG.register(One, name="ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = nd.random.uniform(-self.scale, self.scale, shape=arr.shape,
                                   dtype="float32").astype(arr.dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = nd.random.normal(0.0, self.sigma, shape=arr.shape,
                                  dtype="float32").astype(arr.dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        rows = arr.shape[0]
        cols = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (rows, cols))
        else:
            tmp = _np.random.normal(0.0, 1.0, (rows, cols))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = nd.array((self.scale * q).reshape(arr.shape).astype(_np.float32))


@register
class Xavier(Initializer):
    """reference initializer.py Xavier(rnd_type, factor_type, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier requires ndim>=2, got {shape} for {name}")
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = nd.random.uniform(-scale, scale, shape=shape).astype(arr.dtype)
        else:
            arr[:] = nd.random.normal(0, scale, shape=shape).astype(arr.dtype)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference initializer.py Bilinear)."""

    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, _np.float32).reshape(-1)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(len(weight)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = nd.array(weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, rest 0 (reference initializer.py)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        arr[num_hidden:2 * num_hidden] = self.forget_bias

    _init_default = _init_weight
    _init_bias = _init_weight


class Mixed:
    """Pattern-matched initializer list (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches {name}")


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    cls = _REG.get(name)
    return cls(**kwargs)
