"""Dynamic loss scaler (reference python/mxnet/contrib/amp/loss_scaler.py).

Doubles the scale every `scale_window` clean steps, halves on overflow,
never drops below 1. On TPU the compute dtype is bfloat16, whose exponent
range equals float32 — overflow is rare and scaling is usually a no-op
safety net — but float16 mode keeps full reference behavior.
"""
from __future__ import annotations

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (reference loss_scaler.py
        has_overflow over contrib.multi_all_finite)."""
        from ... import nd

        grads = [p.grad() for p in params if p.grad_req != "null"
                 and p._data is not None]
        if not grads:
            return False
        finite = nd.all_finite(*grads)
        return float(finite.asnumpy()) == 0.0

    def update_scale(self, overflow):
        """Reference loss_scaler.py update_scale."""
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
