"""AMP: automatic mixed precision, bf16-first
(reference python/mxnet/contrib/amp/)."""
from . import lists
from .amp import (convert_hybrid_block, convert_model, init, init_trainer,
                  is_enabled, scale_loss, unscale)
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "convert_model",
           "convert_hybrid_block", "LossScaler", "lists", "is_enabled"]
