"""AMP op lists (reference python/mxnet/contrib/amp/lists/symbol.py, 632 LoC).

Three buckets, reference semantics:
  LOW_PRECISION_OPS — run in the compute dtype (bf16 on TPU: MXU-bound
    matmuls/convs, cheap elementwise that follows them);
  FP32_OPS         — numerically-sensitive, forced to float32;
  WIDEST_OPS       — cast all inputs to the widest dtype present
    (amp_multicast semantics for mixed-dtype binary ops).
Unlisted ops run in whatever dtype arrives.
"""

# the FLOP-heavy ops: these set the speed (reference FP16_FUNCS)
LOW_PRECISION_OPS = [
    "FullyConnected",
    "Convolution",
    "Deconvolution",
    "dot",
    "batch_dot",
    "linalg_gemm",
    "linalg_gemm2",
    "RNN",
]

# numerically-sensitive (reference FP32_FUNCS core; norms/softmax/losses
# keep fp32 statistics)
FP32_OPS = [
    "softmax",
    "log_softmax",
    "softmin",
    "SoftmaxOutput",
    "softmax_cross_entropy",
    "SoftmaxActivation",
    "BatchNorm",
    "LayerNorm",
    "InstanceNorm",
    "GroupNorm",
    "L2Normalization",
    "LRN",
    "mean",
    "sum",
    "prod",
    "norm",
    "CTCLoss",
    "exp",
    "log",
    "log2",
    "log10",
    "log1p",
    "expm1",
    "power",
    "broadcast_power",
    "erfinv",
    "cosh",
    "sinh",
]

# mixed-input binary/ternary ops promote to the widest operand dtype
# (reference WIDEST_TYPE_CASTS -> amp_multicast)
WIDEST_OPS = [
    "broadcast_add",
    "broadcast_sub",
    "broadcast_mul",
    "broadcast_div",
    "broadcast_maximum",
    "broadcast_minimum",
    "broadcast_hypot",
    "concat",
    "stack",
    "where",
]
