"""Automatic mixed precision.

Reference: python/mxnet/contrib/amp/amp.py — `init():250` monkey-patches the
generated op wrappers to insert amp_cast/amp_multicast, `init_trainer:287`
attaches a dynamic LossScaler, `scale_loss` context manager,
`convert_model:508` / `convert_hybrid_block:589` rewrite graphs for
low-precision inference.

TPU-native redesign: the compute dtype is bfloat16 (the MXU's native input
type) instead of float16. There are no generated wrappers to patch — eager
and traced execution both flow through ops.registry.apply_op, so AMP is ONE
dispatch hook there: inputs of listed FLOP-heavy ops are cast to bf16,
numerically-sensitive ops to fp32, mixed-dtype elementwise ops to the widest
operand dtype. The hook applies inside hybridize/jit traces too, so the
whole training step compiles with the casts fused in (the reference gets
this via its symbol-rewrite pass; XLA's fusion does it for free here).
"""
from __future__ import annotations

import logging
import types

from ...base import MXNetError, dtype_np
from .lists import FP32_OPS, LOW_PRECISION_OPS, WIDEST_OPS
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "LossScaler"]

_state = {"on": False, "target_dtype": None}


def _cast_arr(a, dtype):
    import jax.numpy as jnp
    from ...ndarray import NDArray

    if isinstance(a, NDArray):
        if jnp.issubdtype(a._data.dtype, jnp.floating) and \
                a._data.dtype != dtype:
            return a.astype(dtype)
        return a
    if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
            and a.dtype != dtype:
        return a.astype(dtype)
    return a


def _amp_hook(op_name, args, params=None):
    """Dispatch hook installed into ops.registry (registry.AMP_HOOK)."""
    import jax.numpy as jnp

    tgt = _state["target_dtype"]
    cond = _COND_FP32.get(op_name)
    if cond is not None and params is not None:
        pname, values = cond
        if params.get(pname) in values:
            return [_cast_arr(a, jnp.float32) for a in args]
    if op_name in _LOW_SET:
        return [_cast_arr(a, tgt) for a in args]
    if op_name in _FP32_SET:
        return [_cast_arr(a, jnp.float32) for a in args]
    if op_name in _WIDEST_SET:
        dts = [a.dtype for a in args
               if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)]
        if len(set(map(str, dts))) > 1:
            widest = jnp.result_type(*dts)
            return [_cast_arr(a, widest) for a in args]
    return args


_LOW_SET = frozenset(LOW_PRECISION_OPS)
_FP32_SET = frozenset(FP32_OPS)
_WIDEST_SET = frozenset(WIDEST_OPS)
_COND_FP32 = {}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP process-wide (reference amp.py:250).

    conditional_fp32_ops: [(op_name, param_name, [values])] — the op runs
    fp32 when its param takes one of the listed values (reference
    CONDITIONAL_FP32_FUNCS)."""
    global _LOW_SET, _FP32_SET, _COND_FP32
    tgt = dtype_np(target_dtype)
    # each init starts from the defaults — custom lists never leak across
    # inits (or tests)
    _LOW_SET = frozenset(target_precision_ops) \
        if target_precision_ops is not None else frozenset(LOW_PRECISION_OPS)
    _FP32_SET = frozenset(fp32_ops) if fp32_ops is not None \
        else frozenset(FP32_OPS)
    _COND_FP32 = {}
    for entry in (conditional_fp32_ops or []):
        op_name, pname, values = entry
        _COND_FP32[op_name] = (pname, set(values))
    _state["on"] = True
    _state["target_dtype"] = tgt
    from ...ops import registry
    registry.AMP_HOOK = _amp_hook
    logging.info("AMP enabled: compute dtype %s", target_dtype)


def is_enabled():
    return _state["on"]


def _off():
    """Testing hook: disable AMP."""
    from ...ops import registry
    registry.AMP_HOOK = None
    _state["on"] = False


def init_trainer(trainer, init_scale=2.0 ** 16):
    """Attach dynamic loss scaling to a Gluon Trainer
    (reference amp.py:287): step() divides by the current scale and skips
    the update on overflow."""
    from ...gluon.trainer import Trainer

    if not isinstance(trainer, Trainer):
        raise MXNetError("init_trainer expects a gluon Trainer")
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        return trainer
    scaler = LossScaler(init_scale=init_scale)
    trainer._amp_loss_scaler = scaler
    trainer._amp_unscaled = False

    def amp_step(self, batch_size, ignore_stale_grad=False):
        scaler_ = self._amp_loss_scaler
        overflow = scaler_.has_overflow(self._params)
        scaler_.update_scale(overflow)
        if overflow:
            self._amp_unscaled = False
            logging.info("AMP: overflow, skipping step; loss scale -> %g",
                         scaler_.loss_scale)
            return
        # amp.unscale() already divided the grads; don't divide twice
        scale = 1.0 if self._amp_unscaled else scaler_.loss_scale
        self._amp_unscaled = False
        self._optimizer.rescale_grad = self._scale / (batch_size * scale)
        if not self._kv_initialized:
            self._init_kvstore()
        self.allreduce_grads()
        self._update(ignore_stale_grad)

    def amp_update(self, batch_size, ignore_stale_grad=False):
        # same overflow-skip + unscale semantics for the no-allreduce path
        scaler_ = self._amp_loss_scaler
        overflow = scaler_.has_overflow(self._params)
        scaler_.update_scale(overflow)
        if overflow:
            self._amp_unscaled = False
            logging.info("AMP: overflow, skipping update; loss scale -> %g",
                         scaler_.loss_scale)
            return
        scale = 1.0 if self._amp_unscaled else scaler_.loss_scale
        self._amp_unscaled = False
        self._optimizer.rescale_grad = self._scale / (batch_size * scale)
        if not self._kv_initialized:
            self._init_kvstore()
        self._update(ignore_stale_grad)

    trainer.step = types.MethodType(amp_step, trainer)
    trainer.update = types.MethodType(amp_update, trainer)
    return trainer


class _ScaledLoss:
    def __init__(self, loss, trainer):
        self._loss = loss
        self._trainer = trainer

    def __enter__(self):
        scaler = getattr(self._trainer, "_amp_loss_scaler", None)
        if scaler is None:
            raise MXNetError("call amp.init_trainer(trainer) first")
        s = scaler.loss_scale
        if isinstance(self._loss, (list, tuple)):
            return [l * s for l in self._loss]
        return self._loss * s

    def __exit__(self, *exc):
        return False


def scale_loss(loss, trainer):
    """`with amp.scale_loss(loss, trainer) as l: l.backward()`
    (reference amp.py scale_loss)."""
    return _ScaledLoss(loss, trainer)


def unscale(trainer):
    """Divide current gradients by the loss scale (reference amp.py
    unscale) for clipping between backward() and step()."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    s = scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._data is not None:
            g = p.grad()
            g._data = g._data / s
    trainer._amp_unscaled = True


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  excluded_sym_names=None):
    """Low-precision inference conversion for a symbolic model
    (reference amp.py:508): cast parameters feeding listed FLOP-heavy ops;
    the graph itself stays dtype-polymorphic (ops compute in their input
    dtype under XLA)."""
    tgt = dtype_np(target_dtype)
    excluded = set(excluded_sym_names or [])
    from ...symbol.symbol import _topo

    low_args = set()
    for node in _topo(sym._outputs):
        if node.op is not None and node.op.name in _LOW_SET \
                and node.name not in excluded:
            for (inp, _) in node.inputs:
                if inp.op is None:
                    low_args.add(inp.name)
    new_arg = {k: (v.astype(tgt) if k in low_args else v)
               for k, v in arg_params.items()}
    return sym, new_arg, dict(aux_params)


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Cast a HybridBlock for low-precision inference
    (reference amp.py:589): parameters go to bf16 except normalization
    statistics; inputs are cast on entry via a forward pre-hook."""
    from ...gluon import nn
    from ...ndarray import NDArray

    tgt_name = "bfloat16" if "bfloat16" in str(target_dtype) else \
        str(target_dtype)

    def cast_block(b):
        if isinstance(b, (nn.BatchNorm, nn.LayerNorm, nn.InstanceNorm,
                          nn.GroupNorm)):
            return  # keep norm statistics fp32 (reference FP32 list)
        for child in b._children.values():
            cast_block(child)
        for p in b._reg_params.values():
            p.cast(tgt_name)

    cast_block(block)
    tgt = dtype_np(tgt_name)
    orig_forward = block.forward

    def fwd(self, *args):
        cast_args = [a.astype(tgt) if isinstance(a, NDArray) and
                     "float32" in str(a.dtype) else a for a in args]
        return orig_forward(*cast_args)

    block.forward = types.MethodType(fwd, block)
    return block
