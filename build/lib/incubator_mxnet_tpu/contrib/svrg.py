"""SVRG optimization (variance-reduced SGD).

Reference: python/mxnet/contrib/svrg_optimization/ — SVRGModule keeps a
snapshot of the parameters every `update_freq` epochs, the full-dataset
gradient at that snapshot (mu), and corrects every minibatch gradient as
    g_corrected = g_i(w) - g_i(w_snapshot) + mu
(Johnson & Zhang, 2013). The reference implements this with a pair of
Modules and a special _SVRGOptimizer; here the snapshot executor is a
second Module bound to the same Symbol and the correction is applied
in-place on grad_dict before the normal update — no special optimizer
needed, any registered optimizer composes.
"""
from __future__ import annotations

from ..base import MXNetError
from ..module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG gradient correction.

    usage (reference svrg_module.py example):
        mod = SVRGModule(sym, update_freq=2)
        mod.bind(data_shapes=..., label_shapes=...)
        mod.init_params(); mod.init_optimizer(...)
        mod.fit(train_iter, num_epoch=N)   # handles snapshots itself
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), context=None,
                 update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, context=context, **kwargs)
        if int(update_freq) < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = int(update_freq)
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, context=context,
                               **kwargs)
        self._mu = None           # full gradient at the snapshot
        self._last_batch = None

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, None, grad_req)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        self._take_snapshot()

    def _take_snapshot(self):
        """Copy current params into the snapshot module."""
        arg, aux = self.get_params()
        self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                  force_init=True, allow_missing=False)

    def update_full_grads(self, train_data):
        """Compute mu = (1/B) sum over ALL batches of the snapshot's
        gradient (reference svrg_module.py update_full_grads)."""
        train_data.reset()
        n = 0
        sums = {}
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            for name in self._param_names:
                g = self._mod_aux._exec.grad_dict.get(name)
                if g is None:
                    continue
                # accumulate ON DEVICE (XLA async adds) — a host asnumpy()
                # per param per batch would serialize the whole pass
                gd = g._data
                sums[name] = gd if name not in sums else sums[name] + gd
            n += 1
        train_data.reset()
        if n == 0:
            raise MXNetError("update_full_grads: empty train_data")
        from ..ndarray.ndarray import NDArray
        self._mu = {k: NDArray(v / n) for k, v in sums.items()}

    def forward(self, data_batch, is_train=None):
        self._last_batch = data_batch
        super().forward(data_batch, is_train)

    def update(self):
        """Correct grads in place (g - g_snap + mu), then the normal
        optimizer step."""
        if self._mu is not None and self._last_batch is not None:
            self._mod_aux.forward(self._last_batch, is_train=True)
            self._mod_aux.backward()
            for name in self._param_names:
                g = self._exec.grad_dict.get(name)
                gs = self._mod_aux._exec.grad_dict.get(name)
                mu = self._mu.get(name)
                if g is None or gs is None or mu is None:
                    continue
                g._data = (g._data - gs._data + mu._data).astype(g.dtype)
        super().update()

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd", optimizer_params=None,
            initializer=None, num_epoch=None, begin_epoch=0, **kwargs):
        """Epoch loop with snapshot + full-grad refresh every update_freq
        epochs (reference svrg_module.py fit)."""
        if num_epoch is None:
            raise MXNetError("fit needs num_epoch")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        self.init_params(initializer=initializer)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        from ..metric import create as _metric_create
        metric = _metric_create(eval_metric) if isinstance(eval_metric, str) \
            else eval_metric
        from ..model import BatchEndParam
        for epoch in range(begin_epoch, num_epoch):
            if (epoch - begin_epoch) % self.update_freq == 0:
                self._take_snapshot()
                self.update_full_grads(train_data)
            metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward(batch, is_train=True)
                self.backward()
                self.update()
                self.update_metric(metric, batch.label)
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=metric, locals=None)
                    cbs = batch_end_callback if isinstance(
                        batch_end_callback, (list, tuple)) \
                        else [batch_end_callback]
                    for cb in cbs:
                        cb(param)
            if epoch_end_callback is not None:
                epoch_end_callback(epoch, self.symbol, *self.get_params())
            if eval_data is not None:
                res = self.score(eval_data, eval_metric)
                self.logger.info("Epoch[%d] validation: %s", epoch,
                                 dict(res))
        return metric
