"""TensorBoard logging callback.

Reference: python/mxnet/contrib/tensorboard.py (73 LoC LogMetricsCallback
over the `tensorboard` SummaryWriter). The writer dependency is optional;
without it, events fall back to a JSONL file a TensorBoard-compatible
ingester (or any log parser) can consume — nothing in this image may be
pip-installed, so the fallback is the default path here.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Batch-end callback logging eval metrics.

    usage: mod.fit(..., batch_end_callback=LogMetricsCallback(logdir))
    """

    def __init__(self, logging_dir, prefix=None):
        self.logging_dir = logging_dir
        self.prefix = prefix
        self.step = 0
        os.makedirs(logging_dir, exist_ok=True)
        self._writer = None
        try:  # optional real SummaryWriter (tensorboardX / torch.utils)
            from torch.utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(logging_dir)
        except Exception:
            self._file = open(os.path.join(logging_dir, "metrics.jsonl"),
                              "a", buffering=1)

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            tag = f"{self.prefix}-{name}" if self.prefix else name
            if self._writer is not None:
                self._writer.add_scalar(tag, value, self.step)
            else:
                self._file.write(json.dumps(
                    {"tag": tag, "value": float(value), "step": self.step,
                     "ts": time.time()}) + "\n")
