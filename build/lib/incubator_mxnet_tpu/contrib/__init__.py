"""contrib: AMP, quantization, and extended ops
(reference python/mxnet/contrib/)."""


def __getattr__(name):
    import importlib
    lazy = {"amp": ".amp", "quantization": ".quantization", "onnx": ".onnx",
            "text": ".text", "svrg": ".svrg", "svrg_optimization": ".svrg",
            "tensorboard": ".tensorboard"}
    if name in lazy:
        m = importlib.import_module(lazy[name], __name__)
        globals()[name] = m
        return m
    raise AttributeError(f"module 'contrib' has no attribute {name!r}")
