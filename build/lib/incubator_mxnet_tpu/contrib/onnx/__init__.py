"""ONNX interop (reference python/mxnet/contrib/onnx/): export_model
(mx2onnx) and import_model/get_model_metadata (onnx2mx), speaking the
protobuf wire format directly (_proto.py) — no onnx package required."""
from .mx2onnx import export_model
from .onnx2mx import import_model, get_model_metadata

__all__ = ["export_model", "import_model", "get_model_metadata"]
