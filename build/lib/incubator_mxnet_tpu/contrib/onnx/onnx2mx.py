"""ONNX -> Symbol importer.

Reference counterpart: python/mxnet/contrib/onnx/onnx2mx/import_model.py +
import_onnx.py (GraphProto._convert_operator). Returns
(sym, arg_params, aux_params) exactly like the reference's import_model so
the result drops into Module/SymbolBlock.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ... import ndarray as nd
from ...symbol import symbol as sym_mod
from . import _proto as P


class _OnnxNode:
    __slots__ = ("op_type", "inputs", "outputs", "name", "attrs")

    def __init__(self, fields):
        self.inputs = [x.decode("utf-8") for x in fields.get(1, [])]
        self.outputs = [x.decode("utf-8") for x in fields.get(2, [])]
        self.name = fields.get(3, [b""])[0].decode("utf-8")
        self.op_type = fields.get(4, [b""])[0].decode("utf-8")
        self.attrs = {}
        for raw in fields.get(5, []):
            k, v = P.attr_value(P.parse(raw))
            self.attrs[k] = v


def _parse_value_info(raw):
    f = P.parse(raw)
    name = f.get(1, [b""])[0].decode("utf-8")
    shape = []
    if 2 in f:
        tp = P.parse(f[2][0])
        if 1 in tp:  # tensor_type
            tt = P.parse(tp[1][0])
            if 2 in tt:
                shp = P.parse(tt[2][0])
                for draw in shp.get(1, []):
                    d = P.parse(draw)
                    if 1 in d:
                        shape.append(P.as_int64(d[1][0]))
                    else:
                        shape.append(0)
    return name, tuple(shape)


def _parse_graph(raw):
    f = P.parse(raw)
    nodes = [_OnnxNode(P.parse(r)) for r in f.get(1, [])]
    inits = dict(P.tensor_to_array(P.parse(r)) for r in f.get(5, []))
    inputs = [_parse_value_info(r) for r in f.get(11, [])]
    outputs = [_parse_value_info(r) for r in f.get(12, [])]
    return nodes, inits, inputs, outputs


def _load_model_proto(fname):
    with open(fname, "rb") as fh:
        blob = fh.read()
    f = P.parse(blob)
    if 7 not in f:
        raise MXNetError(f"{fname}: no GraphProto in model")
    return _parse_graph(f[7][0])


# --------------------------------------------------------------------------
# per-op converters: fn(node, ins, aux) -> Symbol   (ins are Symbols or
# numpy arrays for initializer-backed inputs)
# --------------------------------------------------------------------------

def _sym_of(x, store):
    """Materialize an initializer input as a bound Variable."""
    if isinstance(x, sym_mod.Symbol):
        return x
    raise MXNetError("expected symbol input")


def _pads2mx(pads, nd_):
    if not pads:
        return (0,) * nd_
    begin, end = pads[:nd_], pads[nd_:]
    if list(begin) != list(end):
        raise MXNetError(f"asymmetric pads {pads} unsupported")
    return tuple(begin)


def _conv(n, ins, g):
    k = n.attrs.get("kernel_shape")
    nd_ = len(k)
    no_bias = len(ins) < 3
    num_filter = g.shape_of(n.inputs[1])[0]
    kw = dict(kernel=tuple(k), stride=tuple(n.attrs.get("strides", (1,) * nd_)),
              dilate=tuple(n.attrs.get("dilations", (1,) * nd_)),
              pad=_pads2mx(n.attrs.get("pads"), nd_),
              num_group=int(n.attrs.get("group", 1)),
              num_filter=int(num_filter), no_bias=no_bias)
    return sym_mod._create(g.op("Convolution"), tuple(ins), kw)


def _deconv(n, ins, g):
    k = n.attrs.get("kernel_shape")
    nd_ = len(k)
    num_filter = g.shape_of(n.inputs[1])[1] * int(n.attrs.get("group", 1))
    kw = dict(kernel=tuple(k), stride=tuple(n.attrs.get("strides", (1,) * nd_)),
              dilate=tuple(n.attrs.get("dilations", (1,) * nd_)),
              pad=_pads2mx(n.attrs.get("pads"), nd_),
              num_group=int(n.attrs.get("group", 1)),
              num_filter=int(num_filter), no_bias=len(ins) < 3)
    return sym_mod._create(g.op("Deconvolution"), tuple(ins), kw)


def _gemm(n, ins, g):
    alpha = float(n.attrs.get("alpha", 1.0))
    beta = float(n.attrs.get("beta", 1.0))
    transB = int(n.attrs.get("transB", 0))
    transA = int(n.attrs.get("transA", 0))
    if alpha == 1.0 and beta == 1.0 and transB == 1 and not transA:
        nh = g.shape_of(n.inputs[1])[0]
        return sym_mod._create(g.op("FullyConnected"), tuple(ins[:3]),
                               dict(num_hidden=int(nh), no_bias=len(ins) < 3,
                                    flatten=False))
    a, b_ = ins[0], ins[1]
    if transA:
        a = sym_mod._create(g.op("transpose"), (a,), {})
    if not transB:
        b_ = sym_mod._create(g.op("transpose"), (b_,), {})
    out = sym_mod._create(g.op("dot"), (a, b_), {})
    if alpha != 1.0:
        out = out * alpha
    if len(ins) > 2:
        c = ins[2] if beta == 1.0 else ins[2] * beta
        out = sym_mod._create(g.op("broadcast_add"), (out, c), {})
    return out


def _pool(mx_type, global_pool):
    def cv(n, ins, g):
        kw = dict(pool_type=mx_type, global_pool=global_pool)
        if not global_pool:
            k = n.attrs["kernel_shape"]
            nd_ = len(k)
            kw.update(kernel=tuple(k),
                      stride=tuple(n.attrs.get("strides", (1,) * nd_)),
                      pad=_pads2mx(n.attrs.get("pads"), nd_))
            if mx_type == "avg":
                # ONNX spec default is 0 (exclude padding from the mean)
                kw["count_include_pad"] = \
                    bool(n.attrs.get("count_include_pad", 0))
        return sym_mod._create(g.op("Pooling"), tuple(ins[:1]), kw)
    return cv


def _bn(n, ins, g):
    return sym_mod._create(
        g.op("BatchNorm"), tuple(ins[:5]),
        dict(eps=float(n.attrs.get("epsilon", 1e-5)),
             momentum=float(n.attrs.get("momentum", 0.9)), fix_gamma=False))


def _simple(mx_op, **fixed):
    def cv(n, ins, g):
        return sym_mod._create(g.op(mx_op), tuple(ins), dict(fixed))
    return cv


def _unary1(mx_op):
    def cv(n, ins, g):
        return sym_mod._create(g.op(mx_op), tuple(ins[:1]), {})
    return cv


def _binary_bcast(mx_op):
    def cv(n, ins, g):
        return sym_mod._create(g.op(mx_op), tuple(ins[:2]), {})
    return cv


def _activationlike(mx_name, attr_map=()):
    def cv(n, ins, g):
        kw = {mk: n.attrs[ok] for ok, mk in attr_map if ok in n.attrs}
        return sym_mod._create(g.op("LeakyReLU"), tuple(ins),
                               dict(act_type=mx_name, **kw))
    return cv


def _softmax(n, ins, g):
    return sym_mod._create(g.op("softmax"), tuple(ins[:1]),
                           dict(axis=int(n.attrs.get("axis", 1))))


def _log_softmax(n, ins, g):
    return sym_mod._create(g.op("log_softmax"), tuple(ins[:1]),
                           dict(axis=int(n.attrs.get("axis", 1))))


def _reshape(n, ins, g):
    shape = g.const_of(n.inputs[1])
    if shape is None:
        raise MXNetError("Reshape with dynamic shape input unsupported")
    return sym_mod._create(g.op("reshape"), tuple(ins[:1]),
                           dict(shape=tuple(int(x) for x in shape)))


def _transpose_cv(n, ins, g):
    perm = n.attrs.get("perm")
    return sym_mod._create(g.op("transpose"), tuple(ins[:1]),
                           dict(axes=tuple(perm)) if perm else {})


def _concat_cv(n, ins, g):
    return sym_mod._create(g.op("Concat"), tuple(ins),
                           dict(dim=int(n.attrs.get("axis", 1)),
                                num_args=len(ins)))


def _clip_cv(n, ins, g):
    lo = n.attrs.get("min", -3.4e38)
    hi = n.attrs.get("max", 3.4e38)
    if len(ins) > 1:  # opset>=11 min/max inputs (must be constants here)
        lo = g.const_of(n.inputs[1]) if len(n.inputs) > 1 and n.inputs[1] else lo
        hi = g.const_of(n.inputs[2]) if len(n.inputs) > 2 and n.inputs[2] else hi
    return sym_mod._create(g.op("clip"), tuple(ins[:1]),
                           dict(a_min=float(np.asarray(lo)),
                                a_max=float(np.asarray(hi))))


def _reduce_cv(mx_op):
    def cv(n, ins, g):
        axes = n.attrs.get("axes")
        kw = dict(keepdims=bool(n.attrs.get("keepdims", 1)))
        if axes is not None:
            kw["axis"] = tuple(axes) if len(axes) > 1 else int(axes[0])
        return sym_mod._create(g.op(mx_op), tuple(ins[:1]), kw)
    return cv


def _cast_cv(n, ins, g):
    to = int(n.attrs["to"])
    return sym_mod._create(g.op("cast"), tuple(ins[:1]),
                           dict(dtype=str(P.ONNX_TO_NP[to])))


def _slice_cv(n, ins, g):
    axes = n.attrs.get("axes")
    starts = n.attrs.get("starts")
    ends = n.attrs.get("ends")
    if axes is None or len(axes) != 1:
        raise MXNetError("only single-axis Slice supported")
    return sym_mod._create(g.op("slice_axis"), tuple(ins[:1]),
                           dict(axis=int(axes[0]), begin=int(starts[0]),
                                end=int(ends[0])))


def _unsqueeze(n, ins, g):
    out = ins[0]
    for ax in sorted(n.attrs.get("axes", [0])):
        out = sym_mod._create(g.op("expand_dims"), (out,),
                              dict(axis=int(ax)))
    return out


def _squeeze_cv(n, ins, g):
    axes = n.attrs.get("axes")
    kw = dict(axis=tuple(axes)) if axes else {}
    return sym_mod._create(g.op("squeeze"), tuple(ins[:1]), kw)


def _pad_cv(n, ins, g):
    pads = n.attrs.get("pads", [])
    nd_ = len(pads) // 2
    pw = []
    for i in range(nd_):
        pw += [int(pads[i]), int(pads[i + nd_])]
    return sym_mod._create(g.op("Pad"), tuple(ins[:1]),
                           dict(mode=n.attrs.get("mode", "constant"),
                                pad_width=tuple(pw),
                                constant_value=float(
                                    n.attrs.get("value", 0.0))))


def _gather(n, ins, g):
    if int(n.attrs.get("axis", 0)) != 0:
        raise MXNetError("Gather axis != 0 unsupported")
    data, idx = ins[0], ins[1]
    idxf = sym_mod._create(g.op("cast"), (idx,), dict(dtype="float32"))
    shp = g.shape_of(n.inputs[0])
    return sym_mod._create(g.op("Embedding"), (idxf, data),
                           dict(input_dim=int(shp[0]),
                                output_dim=int(shp[1])))


def _lrn_cv(n, ins, g):
    return sym_mod._create(g.op("LRN"), tuple(ins[:1]),
                           dict(nsize=int(n.attrs["size"]),
                                alpha=float(n.attrs.get("alpha", 1e-4)),
                                beta=float(n.attrs.get("beta", 0.75)),
                                knorm=float(n.attrs.get("bias", 1.0))))


def _inorm(n, ins, g):
    return sym_mod._create(g.op("InstanceNorm"), tuple(ins[:3]),
                           dict(eps=float(n.attrs.get("epsilon", 1e-5))))


def _dropout_cv(n, ins, g):
    return sym_mod._create(g.op("Dropout"), tuple(ins[:1]),
                           dict(p=float(n.attrs.get("ratio", 0.5))))


def _matmul(n, ins, g):
    return sym_mod._create(g.op("dot"), tuple(ins[:2]), {})


def _identity_cv(n, ins, g):
    return sym_mod._create(g.op("identity"), tuple(ins[:1]), {})


def _sum_n(n, ins, g):
    out = ins[0]
    for x in ins[1:]:
        out = sym_mod._create(g.op("broadcast_add"), (out, x), {})
    return out


def _constant(n, ins, g):
    arr = n.attrs.get("value")
    name = n.outputs[0]
    g.initializers[name] = np.asarray(arr)
    return g.var_for(name)


CONVERTERS = {
    "Conv": _conv,
    "ConvTranspose": _deconv,
    "Gemm": _gemm,
    "MatMul": _matmul,
    "BatchNormalization": _bn,
    "MaxPool": _pool("max", False),
    "AveragePool": _pool("avg", False),
    "GlobalMaxPool": _pool("max", True),
    "GlobalAveragePool": _pool("avg", True),
    "Relu": _unary1("relu"), "Sigmoid": _unary1("sigmoid"),
    "Tanh": _unary1("tanh"),
    "Softplus": _simple("Activation", act_type="softrelu"),
    "Softsign": _unary1("softsign"),
    "Exp": _unary1("exp"), "Log": _unary1("log"), "Sqrt": _unary1("sqrt"),
    "Abs": _unary1("abs"), "Neg": _unary1("negative"),
    "Floor": _unary1("floor"), "Ceil": _unary1("ceil"),
    "Identity": _identity_cv,
    "LeakyRelu": _activationlike("leaky", (("alpha", "slope"),)),
    "Elu": _activationlike("elu", (("alpha", "slope"),)),
    "Selu": _activationlike("selu"),
    "PRelu": _activationlike("prelu"),
    "Softmax": _softmax, "LogSoftmax": _log_softmax,
    "Add": _binary_bcast("broadcast_add"),
    "Sub": _binary_bcast("broadcast_sub"),
    "Mul": _binary_bcast("broadcast_mul"),
    "Div": _binary_bcast("broadcast_div"),
    "Pow": _binary_bcast("broadcast_power"),
    "Max": _binary_bcast("broadcast_maximum"),
    "Min": _binary_bcast("broadcast_minimum"),
    "Sum": _sum_n,
    "Concat": _concat_cv,
    "Flatten": _unary1("Flatten"),
    "Dropout": _dropout_cv,
    "Reshape": _reshape,
    "Transpose": _transpose_cv,
    "Clip": _clip_cv,
    "Cast": _cast_cv,
    "Slice": _slice_cv,
    "Unsqueeze": _unsqueeze,
    "Squeeze": _squeeze_cv,
    "Pad": _pad_cv,
    "Gather": _gather,
    "LRN": _lrn_cv,
    "InstanceNormalization": _inorm,
    "ReduceSum": _reduce_cv("sum"), "ReduceMean": _reduce_cv("mean"),
    "ReduceMax": _reduce_cv("max"), "ReduceMin": _reduce_cv("min"),
    "ReduceProd": _reduce_cv("prod"),
    "Constant": _constant,
}


class _GraphCtx:
    def __init__(self, initializers):
        self.initializers = initializers
        self.sym_map: dict[str, sym_mod.Symbol] = {}
        self._vars: dict[str, sym_mod.Symbol] = {}
        from ...ops.registry import OPS
        self._ops = OPS

    def op(self, name):
        return self._ops.get(name)

    def var_for(self, name):
        if name not in self._vars:
            self._vars[name] = sym_mod.Variable(name)
        return self._vars[name]

    def resolve(self, name):
        if name in self.sym_map:
            return self.sym_map[name]
        return self.var_for(name)

    def shape_of(self, name):
        if name in self.initializers:
            return self.initializers[name].shape
        raise MXNetError(f"shape of non-initializer {name!r} unknown")

    def const_of(self, name):
        return self.initializers.get(name)


def import_model(model_file):
    """Load an ONNX file -> (sym, arg_params, aux_params).

    Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py:import_model.
    """
    nodes, inits, inputs, outputs = _load_model_proto(model_file)
    g = _GraphCtx(inits)

    last = None
    produced_outputs = {}
    for n in nodes:
        cv = CONVERTERS.get(n.op_type)
        if cv is None:
            raise MXNetError(f"ONNX import: unsupported op {n.op_type!r}")
        ins = [g.resolve(i) for i in n.inputs if i]
        out = cv(n, ins, g)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for name, s in zip(n.outputs, outs):
            g.sym_map[name] = s
            produced_outputs[name] = s
        last = outs[0]

    out_syms = [produced_outputs.get(name, g.sym_map.get(name))
                for name, _ in outputs]
    out_syms = [s for s in out_syms if s is not None] or [last]
    sym = out_syms[0] if len(out_syms) == 1 else sym_mod.Group(out_syms)

    aux_names = set(sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for k, v in inits.items():
        (aux_params if k in aux_names else arg_params)[k] = nd.array(v)
    return sym, arg_params, aux_params


def get_model_metadata(model_file):
    """Input/output names+shapes (reference onnx2mx.import_model:
    get_model_metadata)."""
    _, inits, inputs, outputs = _load_model_proto(model_file)
    return {
        "input_tensor_data": [(n, s) for n, s in inputs if n not in inits],
        "output_tensor_data": list(outputs),
    }
