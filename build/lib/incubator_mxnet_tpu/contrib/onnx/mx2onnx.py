"""Symbol/Gluon -> ONNX exporter.

Reference counterpart: python/mxnet/contrib/onnx/mx2onnx/export_model.py +
_op_translations.py (per-op translation table). Same design: walk the
symbol graph in topo order, translate each mxnet op into one or more ONNX
nodes, emit params as initializers. Targets opset 9 (attribute-style Clip/
Pad/Slice), written with the in-repo wire codec (_proto.py) since the onnx
package is not a dependency.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ...symbol.symbol import Symbol, _topo
from . import _proto as P

OPSET = 9


def _tuple(v, n=2, default=1):
    if v is None or v == ():
        return (default,) * n
    if isinstance(v, (int, float)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


class _Builder:
    def __init__(self, params):
        self.params = dict(params or {})
        self.nodes = []          # encoded NodeProto bytes
        self.initializers = []   # encoded TensorProto bytes
        self.init_names = set()
        self.inputs = []         # (name, shape) graph inputs (non-param vars)
        self.shapes = {}         # tensor name -> inferred shape (best effort)
        self._uid = 0

    def uniq(self, hint):
        self._uid += 1
        return f"{hint}_{self._uid}"

    def add_node(self, op_type, inputs, outputs, name=None, **attrs):
        self.nodes.append(P.node(op_type, inputs, outputs,
                                 name=name or self.uniq(op_type.lower()),
                                 **attrs))

    def add_init(self, name, arr):
        if name not in self.init_names:
            self.initializers.append(P.tensor(name, np.asarray(arr)))
            self.init_names.add(name)
        return name

    def const(self, hint, arr):
        return self.add_init(self.uniq(hint), arr)


# --------------------------------------------------------------------------
# per-op translators: fn(b, n, ins, out) emits nodes producing `out`
# --------------------------------------------------------------------------

_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}
_LEAKY = {"leaky": "LeakyRelu", "elu": "Elu", "prelu": "PRelu",
          "selu": "Selu", "gelu": None}


def _conv(b, n, ins, out):
    a = n.attrs
    kernel = _tuple(a.get("kernel"))
    nd = len(kernel)
    pads = _tuple(a.get("pad"), nd, default=0)
    b.add_node("Conv", ins, [out], kernel_shape=list(kernel),
               strides=list(_tuple(a.get("stride"), nd)),
               dilations=list(_tuple(a.get("dilate"), nd)),
               pads=list(pads) * 2, group=int(a.get("num_group", 1)))


def _deconv(b, n, ins, out):
    a = n.attrs
    kernel = _tuple(a.get("kernel"))
    nd = len(kernel)
    b.add_node("ConvTranspose", ins, [out], kernel_shape=list(kernel),
               strides=list(_tuple(a.get("stride"), nd)),
               dilations=list(_tuple(a.get("dilate"), nd)),
               pads=list(_tuple(a.get("pad"), nd, default=0)) * 2,
               group=int(a.get("num_group", 1)))


def _fc(b, n, ins, out):
    a = n.attrs
    data, weight = ins[0], ins[1]
    if a.get("flatten", True):
        flat = b.uniq("flatten")
        b.add_node("Flatten", [data], [flat], axis=1)
        data = flat
    if a.get("no_bias", False) or len(ins) < 3:
        nh = int(a.get("num_hidden"))
        bias = b.const("zero_bias", np.zeros(nh, np.float32))
    else:
        bias = ins[2]
    b.add_node("Gemm", [data, weight, bias], [out], alpha=1.0, beta=1.0,
               transA=0, transB=1)


def _activation(b, n, ins, out):
    act = n.attrs.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"ONNX export: unsupported Activation {act!r}")
    b.add_node(_ACT[act], ins[:1], [out])


def _leaky(b, n, ins, out):
    act = n.attrs.get("act_type", "leaky")
    slope = float(n.attrs.get("slope", 0.25))
    if act == "leaky":
        b.add_node("LeakyRelu", ins[:1], [out], alpha=slope)
    elif act == "elu":
        b.add_node("Elu", ins[:1], [out], alpha=slope)
    elif act == "selu":
        b.add_node("Selu", ins[:1], [out])
    elif act == "prelu":
        b.add_node("PRelu", ins[:2], [out])
    else:
        raise MXNetError(f"ONNX export: unsupported LeakyReLU {act!r}")


def _batchnorm(b, n, ins, out):
    a = n.attrs
    ins = list(ins[:5])
    if a.get("fix_gamma", True):
        # mxnet fix_gamma treats gamma as constant 1; ONNX has no such
        # flag, so bake ones into the scale initializer (reference
        # mx2onnx/_op_translations.py does the same)
        gshape = b.params.get(ins[1])
        gshape = gshape.shape if gshape is not None else None
        if gshape is not None:
            ins[1] = b.const("bn_ones", np.ones(gshape, np.float32))
    b.add_node("BatchNormalization", ins, [out],
               epsilon=float(a.get("eps", 1e-3)),
               momentum=float(a.get("momentum", 0.9)))


def _pooling(b, n, ins, out):
    a = n.attrs
    ptype = a.get("pool_type", "max")
    if a.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError(f"ONNX export: global {ptype} pool unsupported")
        b.add_node(op, ins[:1], [out])
        return
    kernel = _tuple(a.get("kernel"))
    nd = len(kernel)
    kw = dict(kernel_shape=list(kernel),
              strides=list(_tuple(a.get("stride"), nd)),
              pads=list(_tuple(a.get("pad"), nd, default=0)) * 2)
    if ptype == "max":
        b.add_node("MaxPool", ins[:1], [out], **kw)
    elif ptype == "avg":
        cip = 1 if a.get("count_include_pad", True) else 0
        b.add_node("AveragePool", ins[:1], [out], count_include_pad=cip, **kw)
    else:
        raise MXNetError(f"ONNX export: pool_type {ptype!r} unsupported")


def _binary(op_type):
    def tr(b, n, ins, out):
        b.add_node(op_type, ins[:2], [out])
    return tr


def _scalar_op(op_type, reverse=False):
    def tr(b, n, ins, out):
        c = b.const("scalar", np.asarray(float(n.attrs.get("scalar", 0.0)),
                                         np.float32))
        args = [c, ins[0]] if reverse else [ins[0], c]
        b.add_node(op_type, args, [out])
    return tr


def _unary(op_type):
    def tr(b, n, ins, out):
        b.add_node(op_type, ins[:1], [out])
    return tr


def _reshape(b, n, ins, out):
    shape = n.attrs.get("shape", ())
    c = b.const("shape", np.asarray(list(shape), np.int64))
    b.add_node("Reshape", [ins[0], c], [out])


def _transpose(b, n, ins, out):
    axes = n.attrs.get("axes", ())
    kw = {"perm": list(axes)} if axes else {}
    b.add_node("Transpose", ins[:1], [out], **kw)


def _softmax_decomposed(b, x, out, axis, log=False):
    """Spec-correct softmax for any rank/axis: opset-9 Softmax coerces to
    2D after `axis`, which matches mxnet semantics only for 2D inputs —
    everything else is emitted as max/sub/exp/sum/div."""
    mx_ = b.uniq("smax_max")
    sub = b.uniq("smax_sub")
    ex = b.uniq("smax_exp")
    sm = b.uniq("smax_sum")
    b.add_node("ReduceMax", [x], [mx_], axes=[axis], keepdims=1)
    b.add_node("Sub", [x, mx_], [sub])
    b.add_node("Exp", [sub], [ex])
    b.add_node("ReduceSum", [ex], [sm], axes=[axis], keepdims=1)
    if log:
        lg = b.uniq("smax_logsum")
        b.add_node("Log", [sm], [lg])
        b.add_node("Sub", [sub, lg], [out])
    else:
        b.add_node("Div", [ex, sm], [out])


def _softmax_axis(b, n, ins, default_axis=-1):
    axis = int(n.attrs.get("axis", default_axis))
    shp = b.shapes.get(ins[0])
    if shp:
        axis = axis % len(shp)
    return axis, (len(shp) if shp else None)


def _softmax(b, n, ins, out):
    axis, nd_ = _softmax_axis(b, n, ins)
    if nd_ == 2 and axis == 1:
        b.add_node("Softmax", ins[:1], [out], axis=1)
    else:
        _softmax_decomposed(b, ins[0], out, axis)


def _log_softmax(b, n, ins, out):
    axis, nd_ = _softmax_axis(b, n, ins)
    if nd_ == 2 and axis == 1:
        b.add_node("LogSoftmax", ins[:1], [out], axis=1)
    else:
        _softmax_decomposed(b, ins[0], out, axis, log=True)


def _softmax_output(b, n, ins, out):
    shp = b.shapes.get(ins[0])
    if shp is None or len(shp) == 2:
        b.add_node("Softmax", ins[:1], [out], axis=1)
    else:
        _softmax_decomposed(b, ins[0], out, 1)


def _concat(b, n, ins, out):
    b.add_node("Concat", ins, [out], axis=int(n.attrs.get("dim", 1)))


def _dropout(b, n, ins, out):
    b.add_node("Dropout", ins[:1], [out], ratio=float(n.attrs.get("p", 0.5)))


def _clip(b, n, ins, out):
    # one-sided clips are legal (a_min/a_max default None); P.node drops
    # None attrs and opset-9 Clip defaults to +/-3.4e38
    amin, amax = n.attrs.get("a_min"), n.attrs.get("a_max")
    b.add_node("Clip", ins[:1], [out],
               min=float(amin) if amin is not None else None,
               max=float(amax) if amax is not None else None)


def _reduce(op_type):
    def tr(b, n, ins, out):
        axis = n.attrs.get("axis", None)
        kw = {"keepdims": 1 if n.attrs.get("keepdims", False) else 0}
        if axis is not None:
            kw["axes"] = [axis] if isinstance(axis, int) else list(axis)
        b.add_node(op_type, ins[:1], [out], **kw)
    return tr


def _cast(b, n, ins, out):
    dt = np.dtype(n.attrs.get("dtype", "float32"))
    b.add_node("Cast", ins[:1], [out], to=int(P.NP_TO_ONNX[dt]))


def _slice_axis(b, n, ins, out):
    a = n.attrs
    end = a.get("end")
    b.add_node("Slice", ins[:1], [out], axes=[int(a["axis"])],
               starts=[int(a["begin"])],
               ends=[int(end) if end is not None else 2**31 - 1])


def _expand_dims(b, n, ins, out):
    b.add_node("Unsqueeze", ins[:1], [out], axes=[int(n.attrs["axis"])])


def _squeeze(b, n, ins, out):
    ax = n.attrs.get("axis")
    kw = {}
    if ax is not None:
        kw["axes"] = [ax] if isinstance(ax, int) else list(ax)
    b.add_node("Squeeze", ins[:1], [out], **kw)


def _flatten(b, n, ins, out):
    b.add_node("Flatten", ins[:1], [out], axis=1)


def _pad(b, n, ins, out):
    a = n.attrs
    pw = list(a.get("pad_width", ()))
    ndim = len(pw) // 2
    onnx_pads = [pw[2 * i] for i in range(ndim)] + \
                [pw[2 * i + 1] for i in range(ndim)]
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect"}[a.get("mode", "constant")]
    b.add_node("Pad", ins[:1], [out], mode=mode, pads=onnx_pads,
               value=float(a.get("constant_value", 0.0)))


def _embedding(b, n, ins, out):
    cast = b.uniq("cast_idx")
    b.add_node("Cast", [ins[0]], [cast], to=int(P.INT64))
    b.add_node("Gather", [ins[1], cast], [out], axis=0)


def _lrn(b, n, ins, out):
    a = n.attrs
    b.add_node("LRN", ins[:1], [out], alpha=float(a.get("alpha", 1e-4)),
               beta=float(a.get("beta", 0.75)),
               bias=float(a.get("knorm", 2.0)), size=int(a["nsize"]))


def _instance_norm(b, n, ins, out):
    b.add_node("InstanceNormalization", ins[:3], [out],
               epsilon=float(n.attrs.get("eps", 1e-3)))


def _dot(b, n, ins, out):
    if n.attrs.get("transpose_a") or n.attrs.get("transpose_b"):
        raise MXNetError("ONNX export: transposed dot unsupported; "
                         "use linalg_gemm2 semantics via explicit Transpose")
    b.add_node("MatMul", ins[:2], [out])


TRANSLATORS = {
    "Convolution": _conv,
    "Deconvolution": _deconv,
    "FullyConnected": _fc,
    "Activation": _activation,
    "LeakyReLU": _leaky,
    "BatchNorm": _batchnorm,
    "Pooling": _pooling,
    "Flatten": _flatten,
    "flatten": _flatten,
    "Concat": _concat,
    "concat": _concat,
    "Dropout": _dropout,
    "softmax": _softmax,
    "log_softmax": _log_softmax,
    "SoftmaxOutput": _softmax_output,
    "Reshape": _reshape,
    "reshape": _reshape,
    "transpose": _transpose,
    "clip": _clip,
    "cast": _cast,
    "slice_axis": _slice_axis,
    "expand_dims": _expand_dims,
    "squeeze": _squeeze,
    "Pad": _pad,
    "pad": _pad,
    "Embedding": _embedding,
    "LRN": _lrn,
    "InstanceNorm": _instance_norm,
    "dot": _dot,
    "elemwise_add": _binary("Add"), "_plus": _binary("Add"),
    "elemwise_sub": _binary("Sub"), "_minus": _binary("Sub"),
    "elemwise_mul": _binary("Mul"), "_mul": _binary("Mul"),
    "elemwise_div": _binary("Div"), "_div": _binary("Div"),
    "broadcast_add": _binary("Add"), "broadcast_sub": _binary("Sub"),
    "broadcast_mul": _binary("Mul"), "broadcast_div": _binary("Div"),
    "broadcast_maximum": _binary("Max"), "broadcast_minimum": _binary("Min"),
    "broadcast_power": _binary("Pow"),
    "_add": _binary("Add"), "_sub": _binary("Sub"),
    "_plus_scalar": _scalar_op("Add"), "_minus_scalar": _scalar_op("Sub"),
    "_sub_scalar": _scalar_op("Sub"), "_radd_scalar": _scalar_op("Add"),
    "_rmul_scalar": _scalar_op("Mul"),
    "_rsub_scalar": _scalar_op("Sub", reverse=True),
    "_mul_scalar": _scalar_op("Mul"), "_div_scalar": _scalar_op("Div"),
    "_rdiv_scalar": _scalar_op("Div", reverse=True),
    "_power_scalar": _scalar_op("Pow"),
    "relu": _unary("Relu"), "sigmoid": _unary("Sigmoid"),
    "tanh": _unary("Tanh"), "exp": _unary("Exp"), "log": _unary("Log"),
    "sqrt": _unary("Sqrt"), "abs": _unary("Abs"),
    "negative": _unary("Neg"), "floor": _unary("Floor"),
    "ceil": _unary("Ceil"), "identity": _unary("Identity"),
    "_copy": _unary("Identity"), "BlockGrad": _unary("Identity"),
    "stop_gradient": _unary("Identity"),
    "sum": _reduce("ReduceSum"), "mean": _reduce("ReduceMean"),
    "max": _reduce("ReduceMax"), "min": _reduce("ReduceMin"),
    "prod": _reduce("ReduceProd"),
}


def export_model(sym, params, input_shapes, input_dtype=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a Symbol + params dict to an ONNX file.

    Mirrors python/mxnet/contrib/onnx/mx2onnx/export_model.py:export_model:
    `params` merges arg_params and aux_params; variables without a param
    entry become graph inputs, bound positionally to `input_shapes`.
    Returns onnx_file_path.
    """
    from ... import ndarray as _nd
    if isinstance(sym, (list, tuple)):
        raise MXNetError("pass a single Symbol (use Group for multi-output)")
    np_params = {}
    for k, v in (params or {}).items():
        key = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
        np_params[key] = v.asnumpy() if isinstance(v, _nd.NDArray) \
            else np.asarray(v)

    order = _topo(sym._outputs)
    b = _Builder(np_params)

    # tensor name for each (node, out_index)
    def tname(n, oi):
        if n.op is None:
            return n.name
        return f"{n.name}_out{oi}" if oi else f"{n.name}_output"

    in_shapes = list(input_shapes) if isinstance(input_shapes[0],
                                                 (list, tuple)) \
        else [input_shapes]
    data_vars = [n for n in order
                 if n.op is None and n.name not in np_params]
    if len(data_vars) != len(in_shapes):
        raise MXNetError(
            f"got {len(in_shapes)} input shapes for {len(data_vars)} "
            f"graph inputs ({[v.name for v in data_vars]})")

    graph_inputs = []
    for v, shp in zip(data_vars, in_shapes):
        graph_inputs.append(P.value_info(
            v.name, P.NP_TO_ONNX[np.dtype(input_dtype)], shp))

    # best-effort per-tensor shapes so rank-sensitive translators
    # (softmax family) can canonicalize axes
    shape_kwargs0 = {v.name: tuple(shp)
                     for v, shp in zip(data_vars, in_shapes)}
    try:
        internals = sym.get_internals()
        _, int_shapes, _ = internals.infer_shape_partial(**shape_kwargs0)
        for (node, oi), shp in zip(internals._outputs, int_shapes):
            if shp:
                b.shapes[tname(node, oi)] = tuple(shp)
    except Exception:
        pass
    for name, arr in np_params.items():
        b.shapes.setdefault(name, arr.shape)

    for n in order:
        if n.op is None:
            if n.name in np_params:
                b.add_init(n.name, np_params[n.name])
            continue
        tr = TRANSLATORS.get(n.op.name)
        if tr is None:
            raise MXNetError(
                f"ONNX export: no translator for op {n.op.name!r}")
        ins = [tname(i, oi) for i, oi in n.inputs]
        tr(b, n, ins, tname(n, 0))

    # output value_infos with inferred shapes
    shape_kwargs = {v.name: tuple(shp)
                    for v, shp in zip(data_vars, in_shapes)}
    try:
        _, out_shapes, _ = sym.infer_shape(**shape_kwargs)
    except Exception:
        out_shapes = [() for _ in sym._outputs]
    graph_outputs = []
    for (n, oi), shp in zip(sym._outputs, out_shapes):
        graph_outputs.append(P.value_info(
            tname(n, oi), P.NP_TO_ONNX[np.dtype(input_dtype)], shp or ()))

    g = P.graph(b.nodes, "mxnet_tpu_graph", graph_inputs, graph_outputs,
                b.initializers)
    blob = P.model(g, opset=OPSET)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    if verbose:
        print(f"exported {len(b.nodes)} nodes, "
              f"{len(b.initializers)} initializers -> {onnx_file_path}")
    return onnx_file_path
