"""Minimal ONNX protobuf wire-format codec (no onnx/protobuf dependency).

Implements just enough of the protobuf encoding (varint, 32/64-bit, and
length-delimited wire types) to read and write the ONNX message subset the
exporter/importer use: ModelProto, GraphProto, NodeProto, AttributeProto,
TensorProto, ValueInfoProto, TypeProto, TensorShapeProto,
OperatorSetIdProto. Field numbers follow the public onnx.proto3 schema.

Reference counterpart: python/mxnet/contrib/onnx/ relies on the onnx pip
package; that package is not available here, so the wire format is spoken
directly — files written by this codec load in onnxruntime/netron and
files produced by standard onnx exporters parse here.
"""
from __future__ import annotations

import struct

import numpy as np

# TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL, FLOAT16, \
    DOUBLE, UINT32, UINT64, COMPLEX64, COMPLEX128, BFLOAT16 = range(1, 17)

NP_TO_ONNX = {
    np.dtype(np.float32): FLOAT, np.dtype(np.float64): DOUBLE,
    np.dtype(np.float16): FLOAT16, np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64, np.dtype(np.int8): INT8,
    np.dtype(np.uint8): UINT8, np.dtype(np.bool_): BOOL,
}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_GRAPH = 1, 2, 3, 4, 5
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


# --------------------------------------------------------------------------
# low-level writer
# --------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement 64-bit, per protobuf int64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def w_int(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(int(v))


def w_float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", float(v))


def w_bytes(field: int, b: bytes) -> bytes:
    return _key(field, 2) + _varint(len(b)) + b


def w_str(field: int, s: str) -> bytes:
    return w_bytes(field, s.encode("utf-8"))


def w_packed_ints(field: int, vals) -> bytes:
    body = b"".join(_varint(int(v)) for v in vals)
    return w_bytes(field, body)


def w_msg(field: int, body: bytes) -> bytes:
    return w_bytes(field, body)


# --------------------------------------------------------------------------
# low-level reader
# --------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int):
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse(buf: bytes):
    """Parse one message into {field_number: [raw values]}.

    Wire type 0 -> int, 2 -> bytes, 5 -> 4 raw bytes, 1 -> 8 raw bytes.
    Length-delimited fields may be submessages, strings, or packed arrays —
    the caller interprets per schema.
    """
    out: dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def as_int64(v: int) -> int:
    """Interpret a decoded varint as signed int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def unpack_ints(raw: bytes):
    vals, pos = [], 0
    while pos < len(raw):
        v, pos = _read_varint(raw, pos)
        vals.append(as_int64(v))
    return vals


def read_f32(raw: bytes) -> float:
    return struct.unpack("<f", raw)[0]


# --------------------------------------------------------------------------
# ONNX message builders (encode)
# --------------------------------------------------------------------------

def tensor(name: str, arr: np.ndarray) -> bytes:
    """TensorProto with raw_data (little-endian)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in NP_TO_ONNX:
        raise TypeError(f"unsupported dtype {arr.dtype} for ONNX tensor")
    body = b""
    body += w_packed_ints(1, arr.shape)           # dims
    body += w_int(2, NP_TO_ONNX[arr.dtype])        # data_type
    body += w_str(8, name)                         # name
    if arr.dtype == np.bool_:
        raw = arr.astype(np.uint8).tobytes()
    else:
        raw = arr.astype(arr.dtype.newbyteorder("<")).tobytes()
    body += w_bytes(9, raw)                        # raw_data
    return body


def tensor_to_array(fields) -> tuple[str, np.ndarray]:
    dims = []
    for d in fields.get(1, []):
        if isinstance(d, bytes):
            dims.extend(unpack_ints(d))
        else:
            dims.append(as_int64(d))
    dt = fields.get(2, [FLOAT])[0]
    name = fields.get(8, [b""])[0].decode("utf-8")
    np_dt = ONNX_TO_NP.get(dt)
    if np_dt is None:
        raise TypeError(f"unsupported ONNX data_type {dt}")
    if 9 in fields:  # raw_data
        arr = np.frombuffer(fields[9][0], dtype=np_dt.newbyteorder("<"))
        arr = arr.astype(np_dt)
    elif 4 in fields and dt == FLOAT:  # float_data (packed or repeated)
        vals = []
        for chunk in fields[4]:
            if isinstance(chunk, bytes) and len(chunk) % 4 == 0 and len(chunk) != 4:
                vals.extend(struct.unpack(f"<{len(chunk)//4}f", chunk))
            elif isinstance(chunk, bytes):
                vals.append(read_f32(chunk))
        arr = np.asarray(vals, np.float32)
    elif 7 in fields and dt == INT64:  # int64_data
        vals = []
        for chunk in fields[7]:
            if isinstance(chunk, bytes):
                vals.extend(unpack_ints(chunk))
            else:
                vals.append(as_int64(chunk))
        arr = np.asarray(vals, np.int64)
    elif 5 in fields:  # int32_data
        vals = []
        for chunk in fields[5]:
            if isinstance(chunk, bytes):
                vals.extend(unpack_ints(chunk))
            else:
                vals.append(as_int64(chunk))
        arr = np.asarray(vals, np.int32).astype(np_dt)
    else:
        arr = np.zeros(0, np_dt)
    return name, arr.reshape(dims) if dims else arr


def attribute(name: str, value) -> bytes:
    """AttributeProto from a python value (type inferred)."""
    body = w_str(1, name)
    if isinstance(value, bool):
        body += w_int(3, int(value)) + w_int(20, A_INT)
    elif isinstance(value, int):
        body += w_int(3, value) + w_int(20, A_INT)
    elif isinstance(value, float):
        body += w_float(2, value) + w_int(20, A_FLOAT)
    elif isinstance(value, str):
        body += w_bytes(4, value.encode("utf-8")) + w_int(20, A_STRING)
    elif isinstance(value, np.ndarray):
        body += w_msg(5, tensor(name + "_t", value)) + w_int(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            for v in value:
                body += w_float(7, v)
            body += w_int(20, A_FLOATS)
        elif value and isinstance(value[0], str):
            for v in value:
                body += w_bytes(9, v.encode("utf-8"))
            body += w_int(20, A_STRINGS)
        else:
            for v in value:
                body += w_int(8, int(v))
            body += w_int(20, A_INTS)
    else:
        raise TypeError(f"unsupported attribute type {type(value)}")
    return body


def attr_value(fields):
    """Decode an AttributeProto into (name, python value)."""
    name = fields[1][0].decode("utf-8")
    atype = fields.get(20, [0])[0]
    if atype == A_INT or (atype == 0 and 3 in fields):
        return name, as_int64(fields[3][0])
    if atype == A_FLOAT or (atype == 0 and 2 in fields):
        return name, read_f32(fields[2][0])
    if atype == A_STRING or (atype == 0 and 4 in fields):
        return name, fields[4][0].decode("utf-8")
    if atype == A_TENSOR or (atype == 0 and 5 in fields):
        return name, tensor_to_array(parse(fields[5][0]))[1]
    if atype == A_INTS or 8 in fields:
        vals = []
        for chunk in fields.get(8, []):
            if isinstance(chunk, bytes):
                vals.extend(unpack_ints(chunk))
            else:
                vals.append(as_int64(chunk))
        return name, vals
    if atype == A_FLOATS or 7 in fields:
        vals = []
        for chunk in fields.get(7, []):
            if isinstance(chunk, bytes) and len(chunk) > 4:
                vals.extend(struct.unpack(f"<{len(chunk)//4}f", chunk))
            else:
                vals.append(read_f32(chunk))
        return name, vals
    if atype == A_STRINGS or 9 in fields:
        return name, [c.decode("utf-8") for c in fields.get(9, [])]
    return name, None


def node(op_type: str, inputs, outputs, name: str = "", domain: str = "",
         **attrs) -> bytes:
    body = b""
    for i in inputs:
        body += w_str(1, i)
    for o in outputs:
        body += w_str(2, o)
    if name:
        body += w_str(3, name)
    body += w_str(4, op_type)
    for k, v in attrs.items():
        if v is not None:
            body += w_msg(5, attribute(k, v))
    if domain:
        body += w_str(7, domain)
    return body


def value_info(name: str, elem_type: int, shape) -> bytes:
    dims = b""
    for d in shape:
        if isinstance(d, str):
            dims += w_msg(1, w_str(2, d))
        else:
            dims += w_msg(1, w_int(1, int(d)))
    tensor_type = w_int(1, elem_type) + w_msg(2, dims)
    return w_str(1, name) + w_msg(2, w_msg(1, tensor_type))


def graph(nodes, name, inputs, outputs, initializers) -> bytes:
    body = b""
    for n in nodes:
        body += w_msg(1, n)
    body += w_str(2, name)
    for t in initializers:
        body += w_msg(5, t)
    for vi in inputs:
        body += w_msg(11, vi)
    for vi in outputs:
        body += w_msg(12, vi)
    return body


def model(graph_body: bytes, opset: int = 11, producer="incubator-mxnet-tpu",
          ir_version: int = 6) -> bytes:
    body = w_int(1, ir_version)
    body += w_str(2, producer)
    body += w_str(3, "0.1")
    body += w_msg(8, w_str(1, "") + w_int(2, opset))  # opset_import
    body += w_msg(7, graph_body)
    return body
