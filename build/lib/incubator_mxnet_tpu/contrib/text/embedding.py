"""Token embeddings (reference python/mxnet/contrib/text/embedding.py).

Pretrained-file downloads are gated (zero-egress environment): GloVe and
FastText accept a local `pretrained_file_path`; CustomEmbedding loads any
token<delim>vec text file. The registry/create/CompositeEmbedding API
matches the reference.
"""
from __future__ import annotations

import copy
import io

import numpy as _np

from ... import ndarray as nd
from ...base import MXNetError, Registry
from . import vocab as _vocab

__all__ = ["register", "create", "get_pretrained_file_names",
           "CustomEmbedding", "GloVe", "FastText", "CompositeEmbedding"]

_REG = Registry("token_embedding")


def register(embedding_cls):
    """Register a _TokenEmbedding subclass (reference embedding.py:40)."""
    _REG.register(embedding_cls, name=embedding_cls.__name__.lower())
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding by name (reference :63)."""
    cls = _REG.get(embedding_name.lower())
    if cls is None:
        raise MXNetError(f"unknown embedding {embedding_name!r}")
    return cls(**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names per embedding (reference :90)."""
    table = {"glove": GloVe.pretrained_file_names,
             "fasttext": FastText.pretrained_file_names}
    if embedding_name is not None:
        key = embedding_name.lower()
        if key not in table:
            raise MXNetError(f"unknown embedding {embedding_name!r}")
        return table[key]
    return table


class _TokenEmbedding(_vocab.Vocabulary):
    """Vocabulary + idx_to_vec matrix (reference embedding.py:133)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    def _load_embedding(self, pretrained_file_path, elem_delim=" ",
                        init_unknown_vec=None, encoding="utf-8"):
        """Parse `token<delim>v1<delim>v2...` lines (reference :232)."""
        tokens, vecs = [], []
        seen: set = set()
        vec_len = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                if line_num == 0 and len(parts) == 2:
                    # fastText .vec header: "<count> <dim>" (two ints)
                    try:
                        int(parts[0]), int(parts[1])
                        continue
                    except ValueError:
                        pass
                token, elems = parts[0], parts[1:]
                if vec_len is None:
                    vec_len = len(elems)
                elif len(elems) != vec_len:
                    raise MXNetError(
                        f"line {line_num + 1}: dim {len(elems)} != {vec_len}")
                # keep the FIRST occurrence; real files (GloVe 840B) contain
                # duplicate tokens (reference embedding.py:268 does the same)
                if token in self._token_to_idx or token in seen:
                    continue
                seen.add(token)
                tokens.append(token)
                vecs.append([float(e) for e in elems])
        if vec_len is None:
            raise MXNetError(f"no vectors found in {pretrained_file_path}")
        self._vec_len = vec_len
        for t in tokens:
            self._token_to_idx[t] = len(self._idx_to_token)
            self._idx_to_token.append(t)
        mat = _np.zeros((len(self._idx_to_token), vec_len), _np.float32)
        n_special = len(self._idx_to_token) - len(tokens)
        mat[n_special:] = _np.asarray(vecs, _np.float32)
        if init_unknown_vec is not None and n_special:
            mat[:n_special] = init_unknown_vec(shape=(n_special, vec_len)) \
                if callable(init_unknown_vec) else init_unknown_vec
        self._idx_to_vec = nd.array(mat)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Look up vectors; unknown tokens get index 0's vector
        (reference :366)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            toks = [t if t in self._token_to_idx else t.lower()
                    for t in toks]
        idxs = self.to_indices(toks)
        out = nd.take(self._idx_to_vec,
                      nd.array(_np.asarray(idxs, _np.float32)))
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        """In-place update of vectors for known tokens (reference :405)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        for t in toks:
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is unknown; cannot update")
        idxs = [self._token_to_idx[t] for t in toks]
        nv = new_vectors if isinstance(new_vectors, nd.NDArray) \
            else nd.array(_np.asarray(new_vectors, _np.float32))
        if single:
            nv = nv.reshape((1, -1))
        # dedup keeping the LAST row per token (jax scatter with repeated
        # indices is implementation-defined), then device-side row scatter
        last = {}
        for pos, i in enumerate(idxs):
            last[i] = pos
        keep = sorted(last.values())
        if len(keep) != len(idxs):
            nv = nd.take(nv, nd.array(_np.asarray(keep, _np.float32)))
            idxs = [idxs[p] for p in keep]
        self._idx_to_vec[_np.asarray(idxs)] = nv

    def _build_for_vocabulary(self, vocabulary, source):
        """Restrict `source` embedding to `vocabulary`'s tokens
        (reference :305-357)."""
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens
        self._vec_len = source.vec_len
        mat = _np.zeros((len(self), self._vec_len), _np.float32)
        src_vecs = source.idx_to_vec.asnumpy()
        for i, tok in enumerate(self._idx_to_token):
            j = source.token_to_idx.get(tok)
            if j is not None:
                mat[i] = src_vecs[j]
        self._idx_to_vec = nd.array(mat)


@register
class CustomEmbedding(_TokenEmbedding):
    """Load any `token<delim>vec` text file (reference embedding.py:623)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf-8", init_unknown_vec=None, vocabulary=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        if vocabulary is not None:
            self._build_for_vocabulary(vocabulary, copy.copy(self))


class _PretrainedEmbedding(_TokenEmbedding):
    pretrained_file_names: tuple = ()

    def __init__(self, pretrained_file_name=None, pretrained_file_path=None,
                 init_unknown_vec=None, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        if pretrained_file_path is None:
            raise MXNetError(
                f"{type(self).__name__}: pretrained-file download is "
                "unavailable in this environment (zero egress); pass "
                "pretrained_file_path= to a local copy of "
                f"{pretrained_file_name or self.pretrained_file_names[:3]}")
        self._load_embedding(pretrained_file_path, " ", init_unknown_vec)
        if vocabulary is not None:
            self._build_for_vocabulary(vocabulary, copy.copy(self))


@register
class GloVe(_PretrainedEmbedding):
    """GloVe vectors (reference embedding.py:469). Local-file only here."""
    pretrained_file_names = ("glove.42B.300d.txt", "glove.6B.50d.txt",
                             "glove.6B.100d.txt", "glove.6B.200d.txt",
                             "glove.6B.300d.txt", "glove.840B.300d.txt")


@register
class FastText(_PretrainedEmbedding):
    """fastText vectors (reference embedding.py:541). Local-file only."""
    pretrained_file_names = ("wiki.simple.vec", "wiki.en.vec")


class CompositeEmbedding(_TokenEmbedding):
    """Concatenate several embeddings over one vocabulary
    (reference embedding.py:665)."""

    def __init__(self, vocabulary, token_embeddings):
        super().__init__()
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens
        mats = []
        for emb in token_embeddings:
            part = _np.zeros((len(self), emb.vec_len), _np.float32)
            src = emb.idx_to_vec.asnumpy()
            for i, tok in enumerate(self._idx_to_token):
                j = emb.token_to_idx.get(tok)
                if j is not None:
                    part[i] = src[j]
            mats.append(part)
        full = _np.concatenate(mats, axis=1)
        self._vec_len = full.shape[1]
        self._idx_to_vec = nd.array(full)
