"""Text utilities (reference python/mxnet/contrib/text/): Vocabulary and
token embeddings."""
from . import embedding, utils, vocab
from .vocab import Vocabulary

__all__ = ["vocab", "embedding", "utils", "Vocabulary"]
