// Native RecordIO codec + threaded prefetching reader.
//
// Reference: dmlc-core's recordio (src/io/ in the reference tree uses
// dmlc::RecordIOWriter/Reader; framing documented at
// python/mxnet/recordio.py) and the background PrefetcherIter
// (src/io/iter_prefetcher.h:47 over dmlc::ThreadedIter:142).
//
// Frame: [uint32 magic 0xced7230a][uint32 lrecord][payload][pad to 4B]
//   lrecord = (cflag << 29) | length
//   cflag: 0 = complete, 1 = begin, 2 = middle, 3 = end (multipart for
//   payloads >= 2^29 bytes).
//
// Exposed as a flat C ABI consumed via ctypes (no pybind11 in the image).
// Each handle owns the buffer returned by its read call; the pointer stays
// valid until the next read on the same handle or close.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;
constexpr uint64_t kChunk = (1ull << 29) - 4;  // payload per physical record

// mutex-guarded global (NOT thread_local: the prefetcher worker thread must
// surface read errors to the consumer thread's mxtpu_last_error call)
std::mutex g_error_mu;
std::string g_last_error;
thread_local std::string t_error_copy;

void set_error(const std::string& msg) {
  std::lock_guard<std::mutex> lk(g_error_mu);
  g_last_error = msg;
}

const char* get_error() {
  std::lock_guard<std::mutex> lk(g_error_mu);
  t_error_copy = g_last_error;
  return t_error_copy.c_str();
}

struct Stream {
  FILE* f = nullptr;
  bool writable = false;
  std::string buf;  // last full (reassembled) record for readers
};

// one physical record; returns 1 ok, 0 eof, -1 error
int read_physical(FILE* f, uint32_t* cflag, std::string* out) {
  uint32_t header[2];
  size_t n = fread(header, 1, 8, f);
  if (n == 0) return 0;
  if (n < 8) { set_error("truncated record header"); return -1; }
  if (header[0] != kMagic) { set_error("bad record magic"); return -1; }
  *cflag = header[1] >> 29;
  uint32_t len = header[1] & kLenMask;
  out->resize(len);
  if (len && fread(&(*out)[0], 1, len, f) != len) {
    set_error("truncated record payload");
    return -1;
  }
  uint32_t pad = (4 - (len & 3)) & 3;
  if (pad) {
    char skip[4];
    if (fread(skip, 1, pad, f) != pad) {
      set_error("truncated record padding");
      return -1;
    }
  }
  return 1;
}

// full logical record with multipart reassembly; 1 ok, 0 eof, -1 error
int read_logical(FILE* f, std::string* out) {
  uint32_t cflag = 0;
  int rc = read_physical(f, &cflag, out);
  if (rc <= 0) return rc;
  if (cflag == 0) return 1;
  if (cflag != 1) { set_error("multipart record starts mid-stream"); return -1; }
  std::string part;
  while (true) {
    rc = read_physical(f, &cflag, &part);
    if (rc == 0) { set_error("truncated multipart record"); return -1; }
    if (rc < 0) return -1;
    out->append(part);
    if (cflag == 3) return 1;
    if (cflag != 2) { set_error("unexpected cflag inside multipart"); return -1; }
  }
}

int write_physical(FILE* f, uint32_t cflag, const char* data, uint64_t len) {
  uint32_t header[2] = {kMagic,
                        (cflag << 29) | static_cast<uint32_t>(len & kLenMask)};
  if (fwrite(header, 1, 8, f) != 8) return -1;
  if (len && fwrite(data, 1, len, f) != len) return -1;
  uint32_t pad = (4 - (len & 3)) & 3;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, f) != pad) return -1;
  return 0;
}

struct Prefetcher {
  FILE* f = nullptr;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<std::string> queue;
  size_t depth = 4;
  bool done = false;     // producer finished (eof or error)
  bool stop = false;     // consumer closing
  int status = 1;        // sticky producer status (0 eof, -1 error)
  std::string buf;       // consumer-owned last record

  void run() {
    while (true) {
      std::string rec;
      int rc = read_logical(f, &rec);
      std::unique_lock<std::mutex> lk(mu);
      if (rc <= 0) {
        status = rc;
        done = true;
        cv_get.notify_all();
        return;
      }
      cv_put.wait(lk, [&] { return queue.size() < depth || stop; });
      if (stop) return;
      queue.emplace_back(std::move(rec));
      cv_get.notify_one();
    }
  }
};

}  // namespace

extern "C" {

const char* mxtpu_last_error() { return get_error(); }

void* mxtpu_rio_open_read(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) { set_error("cannot open for read"); return nullptr; }
  auto* s = new Stream();
  s->f = f;
  s->writable = false;
  return s;
}

void* mxtpu_rio_open_write(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) { set_error("cannot open for write"); return nullptr; }
  auto* s = new Stream();
  s->f = f;
  s->writable = true;
  return s;
}

int mxtpu_rio_write(void* h, const char* data, uint64_t len) {
  auto* s = static_cast<Stream*>(h);
  if (!s->writable) { set_error("handle not writable"); return -1; }
  if (len <= kLenMask) {
    return write_physical(s->f, 0, data, len);
  }
  uint64_t off = 0, n = (len + kChunk - 1) / kChunk, i = 0;
  for (; off < len; off += kChunk, ++i) {
    uint64_t part = (len - off < kChunk) ? (len - off) : kChunk;
    uint32_t cflag = (i == 0) ? 1u : ((i == n - 1) ? 3u : 2u);
    if (write_physical(s->f, cflag, data + off, part) != 0) return -1;
  }
  return 0;
}

// 1 = record returned, 0 = eof, -1 = error
int mxtpu_rio_read(void* h, const char** out, uint64_t* len) {
  auto* s = static_cast<Stream*>(h);
  int rc = read_logical(s->f, &s->buf);
  if (rc == 1) {
    *out = s->buf.data();
    *len = s->buf.size();
  }
  return rc;
}

uint64_t mxtpu_rio_tell(void* h) {
  auto* s = static_cast<Stream*>(h);
  return static_cast<uint64_t>(ftello(s->f));
}

int mxtpu_rio_seek(void* h, uint64_t pos) {
  auto* s = static_cast<Stream*>(h);
  return fseeko(s->f, static_cast<off_t>(pos), SEEK_SET);
}

void mxtpu_rio_close(void* h) {
  auto* s = static_cast<Stream*>(h);
  if (s->f) fclose(s->f);
  delete s;
}

// Scan a .rec file and write "<i>\t<offset>" lines; returns record count
// or -1 (the fast path behind tools/rec2idx, reference tools/rec2idx.py).
long long mxtpu_recordio_index(const char* path, const char* idx_out) {
  FILE* f = fopen(path, "rb");
  if (!f) { set_error("cannot open for read"); return -1; }
  FILE* out = fopen(idx_out, "w");
  if (!out) { fclose(f); set_error("cannot open idx for write"); return -1; }
  long long count = 0;
  std::string rec;
  while (true) {
    uint64_t pos = static_cast<uint64_t>(ftello(f));
    int rc = read_logical(f, &rec);
    if (rc == 0) break;
    if (rc < 0) { count = -1; break; }
    fprintf(out, "%lld\t%llu\n", count, (unsigned long long)pos);
    ++count;
  }
  fclose(f);
  fclose(out);
  return count;
}

void* mxtpu_prefetch_open(const char* path, int depth) {
  FILE* f = fopen(path, "rb");
  if (!f) { set_error("cannot open for read"); return nullptr; }
  auto* p = new Prefetcher();
  p->f = f;
  p->depth = depth > 0 ? static_cast<size_t>(depth) : 4;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// 1 = record, 0 = eof, -1 = error
int mxtpu_prefetch_next(void* h, const char** out, uint64_t* len) {
  auto* p = static_cast<Prefetcher*>(h);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_get.wait(lk, [&] { return !p->queue.empty() || p->done; });
  if (p->queue.empty()) return p->status;
  p->buf = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_put.notify_one();
  *out = p->buf.data();
  *len = p->buf.size();
  return 1;
}

void mxtpu_prefetch_close(void* h) {
  auto* p = static_cast<Prefetcher*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_put.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  if (p->f) fclose(p->f);
  delete p;
}

}  // extern "C"
