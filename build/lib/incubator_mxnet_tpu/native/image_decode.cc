// Native JPEG decode + resize + mirror batch kernel.
//
// Reference: src/io/iter_image_recordio_2.cc (multi-threaded OpenCV
// imdecode + DefaultImageAugmenter). TPU-native equivalent: libjpeg
// decompress straight into a caller-provided HWC uint8 batch buffer with
// bilinear resize and optional horizontal mirror, one worker thread per
// shard of the batch. Color normalization stays on the (vectorized)
// python side — it fuses into the host->device cast anyway.
//
// Exposed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <csetjmp>
#include <cstdio>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void err_exit(j_common_ptr cinfo) {
  ErrMgr* e = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// decode buf into an RGB HWC buffer; returns {w, h} or {0, 0} on error
bool decode_rgb(const uint8_t* buf, long len, std::vector<uint8_t>* pix,
                int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  pix->resize(static_cast<size_t>(*w) * *h * 3);
  JSAMPROW row;
  while (cinfo.output_scanline < cinfo.output_height) {
    row = pix->data() + static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// bilinear resize of a sub-window (cx, cy, cw, ch) of src (sw x sh HWC
// uint8) into dst (oh x ow x 3), optional mirror
void resize_bilinear(const uint8_t* src, int sw, int cx, int cy, int cw,
                     int ch, uint8_t* dst, int ow, int oh, bool mirror) {
  const float sx = ow > 1 ? static_cast<float>(cw - 1) / (ow - 1) : 0.f;
  const float sy = oh > 1 ? static_cast<float>(ch - 1) / (oh - 1) : 0.f;
  for (int y = 0; y < oh; ++y) {
    const float fy = y * sy;
    int y0 = static_cast<int>(fy);
    if (y0 > ch - 1) y0 = ch - 1;
    const int y1 = y0 + 1 < ch ? y0 + 1 : ch - 1;
    const float wy = fy - y0;
    const size_t r0 = static_cast<size_t>(cy + y0) * sw;
    const size_t r1 = static_cast<size_t>(cy + y1) * sw;
    for (int x = 0; x < ow; ++x) {
      const float fx = x * sx;
      int x0 = static_cast<int>(fx);
      if (x0 > cw - 1) x0 = cw - 1;
      const int x1 = x0 + 1 < cw ? x0 + 1 : cw - 1;
      const float wx = fx - x0;
      const int ox = mirror ? (ow - 1 - x) : x;
      uint8_t* d = dst + (static_cast<size_t>(y) * ow + ox) * 3;
      const uint8_t* p00 = src + (r0 + cx + x0) * 3;
      const uint8_t* p01 = src + (r0 + cx + x1) * 3;
      const uint8_t* p10 = src + (r1 + cx + x0) * 3;
      const uint8_t* p11 = src + (r1 + cx + x1) * 3;
      for (int c = 0; c < 3; ++c) {
        const float v = (1 - wy) * ((1 - wx) * p00[c] + wx * p01[c]) +
                        wy * ((1 - wx) * p10[c] + wx * p11[c]);
        d[c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// decode one JPEG to (oh, ow, 3) uint8 HWC; center_crop selects the
// python CenterCropAug semantics (centered target-aspect crop, then
// resize — image.py center_crop/scale_down), else a full-frame resize.
int mxtpu_jpeg_decode_resize(const uint8_t* buf, long len, int oh, int ow,
                             int mirror, int center_crop, uint8_t* out) {
  std::vector<uint8_t> pix;
  int w = 0, h = 0;
  if (!decode_rgb(buf, len, &pix, &w, &h) || w <= 0 || h <= 0) return 1;
  int cx = 0, cy = 0, cw = w, ch = h;
  if (center_crop) {
    // scale_down((w, h), (ow, oh)): shrink the TARGET box to fit inside
    // the source, preserving the target's aspect ratio
    float tw = ow, th = oh;
    if (h < th) { tw = tw * h / th; th = h; }
    if (w < tw) { th = th * w / tw; tw = w; }
    cw = static_cast<int>(tw) > 0 ? static_cast<int>(tw) : 1;
    ch = static_cast<int>(th) > 0 ? static_cast<int>(th) : 1;
    cx = (w - cw) / 2;
    cy = (h - ch) / 2;
  }
  resize_bilinear(pix.data(), w, cx, cy, cw, ch, out, ow, oh, mirror != 0);
  return 0;
}

// batch variant: bufs[i] has lens[i] bytes; out is (n, oh, ow, 3) uint8.
// mirrors may be null. Returns number of failed decodes.
int mxtpu_jpeg_decode_batch(const uint8_t** bufs, const long* lens, int n,
                            int oh, int ow, const int* mirrors,
                            int center_crop, uint8_t* out, int nthreads) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n) nthreads = n;
  std::vector<int> fails(nthreads, 0);
  const size_t item = static_cast<size_t>(oh) * ow * 3;
  std::vector<std::thread> workers;
  for (int t = 0; t < nthreads; ++t) {
    workers.emplace_back([&, t]() {
      for (int i = t; i < n; i += nthreads) {
        const int m = mirrors ? mirrors[i] : 0;
        if (mxtpu_jpeg_decode_resize(bufs[i], lens[i], oh, ow, m,
                                     center_crop, out + item * i) != 0) {
          std::memset(out + item * i, 0, item);
          ++fails[t];
        }
      }
    });
  }
  for (auto& th : workers) th.join();
  int total = 0;
  for (int f : fails) total += f;
  return total;
}

}  // extern "C"
