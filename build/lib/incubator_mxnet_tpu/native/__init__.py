"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its IO/runtime layer in C++ (dmlc-core recordio,
src/io/iter_prefetcher.h); the TPU build does the same for the host-side
pieces XLA does not cover: record framing and background file prefetch.

The shared library builds on demand with the toolchain baked into the
image (g++); `load()` returns None if unavailable so every caller keeps a
pure-python fallback.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libmxtpu.so")
_SRC = [os.path.join(_HERE, "recordio.cc"),
        os.path.join(_HERE, "image_decode.cc")]

_lock = threading.Lock()
_lib = None
_tried = False


_NOJPEG_MARK = _SO + ".nojpeg"


def build(force=False):
    """Compile libmxtpu.so (idempotent; returns path or None)."""
    with _lock:
        if os.path.exists(_SO) and not force \
                and not os.path.exists(_NOJPEG_MARK):
            # a jpeg-less fallback build is NOT cached: retry the full
            # build each process so installing libjpeg later takes effect
            src_m = max(os.path.getmtime(s) for s in _SRC)
            if os.path.getmtime(_SO) >= src_m:
                return _SO
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               "-o", _SO] + _SRC + ["-ljpeg"]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            if os.path.exists(_NOJPEG_MARK):
                os.remove(_NOJPEG_MARK)
        except Exception:
            # libjpeg may be absent on some hosts: build without the decode
            # unit so the recordio codec still loads
            try:
                subprocess.run(["g++", "-O2", "-std=c++17", "-shared",
                                "-fPIC", "-pthread", "-o", _SO, _SRC[0]],
                               check=True, capture_output=True, timeout=120)
                open(_NOJPEG_MARK, "w").close()
            except Exception:
                return None
        return _SO if os.path.exists(_SO) else None


def load():
    """Load (building if needed) the native library; None on failure."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        return None
    _tried = True
    so = build()
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.mxtpu_last_error.restype = ctypes.c_char_p
    lib.mxtpu_rio_open_read.restype = ctypes.c_void_p
    lib.mxtpu_rio_open_read.argtypes = [ctypes.c_char_p]
    lib.mxtpu_rio_open_write.restype = ctypes.c_void_p
    lib.mxtpu_rio_open_write.argtypes = [ctypes.c_char_p]
    lib.mxtpu_rio_write.restype = ctypes.c_int
    lib.mxtpu_rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
    lib.mxtpu_rio_read.restype = ctypes.c_int
    lib.mxtpu_rio_read.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_char_p),
                                   ctypes.POINTER(ctypes.c_uint64)]
    lib.mxtpu_rio_tell.restype = ctypes.c_uint64
    lib.mxtpu_rio_tell.argtypes = [ctypes.c_void_p]
    lib.mxtpu_rio_seek.restype = ctypes.c_int
    lib.mxtpu_rio_seek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.mxtpu_rio_close.argtypes = [ctypes.c_void_p]
    lib.mxtpu_recordio_index.restype = ctypes.c_longlong
    lib.mxtpu_recordio_index.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.mxtpu_prefetch_open.restype = ctypes.c_void_p
    lib.mxtpu_prefetch_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.mxtpu_prefetch_next.restype = ctypes.c_int
    lib.mxtpu_prefetch_next.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_char_p),
                                        ctypes.POINTER(ctypes.c_uint64)]
    lib.mxtpu_prefetch_close.argtypes = [ctypes.c_void_p]
    try:
        lib.mxtpu_jpeg_decode_batch.restype = ctypes.c_int
        lib.mxtpu_jpeg_decode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_long),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_void_p,
            ctypes.c_int]
        lib.mxtpu_jpeg_decode_resize.restype = ctypes.c_int
        lib.mxtpu_jpeg_decode_resize.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
        lib.has_jpeg = True
    except AttributeError:
        lib.has_jpeg = False
    _lib = lib
    return lib


def decode_jpeg_batch(bufs, height, width, mirrors=None, center_crop=False,
                      nthreads=4):
    """Decode a list of JPEG byte strings to an (n, H, W, 3) uint8 array
    via the C++ libjpeg pipeline (reference iter_image_recordio_2.cc decode
    threads). center_crop reproduces the python CenterCropAug (centered
    target-aspect crop then resize); otherwise a full-frame resize.
    Returns None when the native path is unavailable — callers fall back
    to PIL."""
    import numpy as np
    lib = load()
    if lib is None or not getattr(lib, "has_jpeg", False):
        return None
    n = len(bufs)
    if n == 0:
        return np.zeros((0, height, width, 3), np.uint8)
    arr_bufs = (ctypes.c_char_p * n)(*bufs)
    arr_lens = (ctypes.c_long * n)(*[len(b) for b in bufs])
    arr_mirr = None
    if mirrors is not None:
        arr_mirr = (ctypes.c_int * n)(*[int(m) for m in mirrors])
    out = np.empty((n, height, width, 3), np.uint8)
    fails = lib.mxtpu_jpeg_decode_batch(
        arr_bufs, arr_lens, n, height, width, arr_mirr,
        1 if center_crop else 0, out.ctypes.data_as(ctypes.c_void_p),
        int(nthreads))
    if fails:
        return None     # corrupt input: let the PIL path raise usefully
    return out


class NativeRecordReader:
    """Sequential logical-record reader over the C++ codec."""

    def __init__(self, path, prefetch=0):
        lib = load()
        if lib is None:
            raise OSError("native library unavailable")
        self._lib = lib
        self._pf = prefetch > 0
        p = path.encode()
        self._h = (lib.mxtpu_prefetch_open(p, prefetch) if self._pf
                   else lib.mxtpu_rio_open_read(p))
        if not self._h:
            raise OSError(lib.mxtpu_last_error().decode())

    def read(self):
        out = ctypes.c_char_p()
        n = ctypes.c_uint64()
        fn = self._lib.mxtpu_prefetch_next if self._pf \
            else self._lib.mxtpu_rio_read
        rc = fn(self._h, ctypes.byref(out), ctypes.byref(n))
        if rc == 0:
            return None
        if rc < 0:
            raise IOError(self._lib.mxtpu_last_error().decode())
        return ctypes.string_at(out, n.value)

    def tell(self):
        if self._pf:
            raise IOError("tell() unsupported on prefetching reader")
        return self._lib.mxtpu_rio_tell(self._h)

    def seek(self, pos):
        if self._pf:
            raise IOError("seek() unsupported on prefetching reader")
        if self._lib.mxtpu_rio_seek(self._h, pos) != 0:
            raise IOError(f"seek to {pos} failed")

    def close(self):
        if self._h:
            (self._lib.mxtpu_prefetch_close if self._pf
             else self._lib.mxtpu_rio_close)(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordWriter:
    def __init__(self, path):
        lib = load()
        if lib is None:
            raise OSError("native library unavailable")
        self._lib = lib
        self._h = lib.mxtpu_rio_open_write(path.encode())
        if not self._h:
            raise OSError(lib.mxtpu_last_error().decode())

    def write(self, data):
        data = bytes(data)
        rc = self._lib.mxtpu_rio_write(self._h, data, len(data))
        if rc != 0:
            raise IOError("record write failed")

    def tell(self):
        return self._lib.mxtpu_rio_tell(self._h)

    def close(self):
        if self._h:
            self._lib.mxtpu_rio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def build_index(rec_path, idx_path):
    """Scan a .rec file, writing the .idx sidecar; returns record count."""
    lib = load()
    if lib is None:
        return None
    n = lib.mxtpu_recordio_index(rec_path.encode(), idx_path.encode())
    if n < 0:
        raise IOError(lib.mxtpu_last_error().decode())
    return n
