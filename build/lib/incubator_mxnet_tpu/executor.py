"""Executor: a Symbol bound to arrays, compiled with jax.jit.

Reference: python/mxnet/executor.py (Executor wrapper) over
GraphExecutor::Init/Forward/Backward (src/executor/graph_executor.cc:388,78,91).
The reference plans memory, attaches per-node engine ops, and bulks segments;
here `bind` closes the graph over its argument arrays and hands the whole
program to XLA — memory planning, fusion, and scheduling are the compiler's
job (SURVEY §7: GraphExecutor simple_bind -> AOT jit compile).

Semantics kept from the reference:
  * grad_req per-argument: write / add / null,
  * backward() with no out_grads seeds ones (loss-head ops like SoftmaxOutput
    ignore the seed by construction, src/operator/softmax_output-inl.h),
  * auxiliary states (BatchNorm moving stats) update on is_train forward with
    the op's momentum — the reference mutates them inside the kernel
    (src/operator/nn/batch_norm.cc:417), we apply the same update functionally,
  * dropout masks agree between forward and backward: the backward executable
    replays the forward's PRNG key.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError, dtype_np
from .ndarray import NDArray
from .ndarray import random as _rnd

__all__ = ["Executor"]


def _as_nd(x, dtype=_np.float32):
    if isinstance(x, NDArray):
        return x
    from .ndarray import array
    return array(x, dtype=getattr(x, "dtype", dtype))


class Executor:
    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        from .symbol.symbol import AUX_INPUTS, _topo

        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self._arg_names = arg_names
        self._aux_names = aux_names

        if args is None:
            raise MXNetError("bind requires args (dict or list)")
        if isinstance(args, dict):
            missing = [n for n in arg_names if n not in args]
            if missing:
                raise MXNetError(f"bind: missing args {missing}")
            self.arg_dict = {n: _as_nd(args[n]) for n in arg_names}
        else:
            if len(args) != len(arg_names):
                raise MXNetError(
                    f"bind: expected {len(arg_names)} args, got {len(args)}")
            self.arg_dict = {n: _as_nd(a) for n, a in zip(arg_names, args)}

        if aux_states is None:
            aux_states = {}
        if isinstance(aux_states, dict):
            self.aux_dict = {n: _as_nd(aux_states[n]) for n in aux_names
                             if n in aux_states}
            missing = [n for n in aux_names if n not in self.aux_dict]
            if missing:
                raise MXNetError(f"bind: missing aux states {missing}")
        else:
            self.aux_dict = {n: _as_nd(a) for n, a in zip(aux_names, aux_states)}

        # grad bookkeeping
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        self.grad_dict = {}
        if args_grad is not None:
            if isinstance(args_grad, dict):
                self.grad_dict = {n: _as_nd(g) for n, g in args_grad.items()}
            else:
                self.grad_dict = {n: _as_nd(g)
                                  for n, g in zip(arg_names, args_grad)}
        for n in arg_names:
            if self._grad_req.get(n, "null") != "null" and n not in self.grad_dict:
                a = self.arg_dict[n]
                from .ndarray import zeros
                self.grad_dict[n] = zeros(a.shape, dtype=a.dtype)

        # BatchNorm aux wiring: node name -> (momentum, mean_var_name, var_name)
        self._bn_wiring = {}
        for node in _topo(symbol._outputs):
            if node.op is not None and node.op.name in AUX_INPUTS:
                aux_argnames = AUX_INPUTS[node.op.name]
                names = {}
                for (inp, _), aname in zip(node.inputs, node.arg_names):
                    if aname in aux_argnames and inp.op is None:
                        names[aname] = inp.name
                if len(names) == len(aux_argnames):
                    self._bn_wiring[node.name] = (
                        float(node.attrs.get("momentum", 0.9)),
                        names[aux_argnames[0]], names[aux_argnames[1]],
                        bool(node.attrs.get("use_global_stats", False)))

        self.outputs = []
        self._monitor_callback = None
        self._jit = {}          # is_train -> jitted forward
        self._jit_bwd = None
        self._last = None       # (rng, arg_vals, aux_vals) of last train fwd

    # -- convenience views --------------------------------------------------
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    # -- compile ------------------------------------------------------------
    def _forward_fn(self, is_train):
        fn = self._jit.get(is_train)
        if fn is None:
            import jax
            run = self._symbol._build_eval(training=is_train)

            def f(arg_vals, aux_vals, rng):
                bindings = dict(arg_vals)
                bindings.update(aux_vals)
                outs, stats = run(bindings, rng)
                new_aux = {}
                if is_train:
                    for node_name, (mom, mname, vname, use_global) in \
                            self._bn_wiring.items():
                        if use_global or node_name not in stats:
                            continue
                        bm, bv = stats[node_name]
                        new_aux[mname] = mom * bindings[mname] + (1 - mom) * bm
                        new_aux[vname] = mom * bindings[vname] + (1 - mom) * bv
                return outs, new_aux

            fn = jax.jit(f)
            self._jit[is_train] = fn
        return fn

    def _backward_fn(self):
        if self._jit_bwd is None:
            import jax
            run = self._symbol._build_eval(training=True)
            wrt = [n for n in self._arg_names
                   if self._grad_req.get(n, "null") != "null"]
            self._wrt = wrt

            def f(diff_vals, fixed_vals, aux_vals, rng, cts):
                def fwd(dv):
                    bindings = dict(fixed_vals)
                    bindings.update(aux_vals)
                    bindings.update(dv)
                    outs, _ = run(bindings, rng)
                    return tuple(outs)

                _, vjp_fn = jax.vjp(fwd, diff_vals)
                return vjp_fn(tuple(cts))[0]

            self._jit_bwd = jax.jit(f)
        return self._jit_bwd

    # -- run ----------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k!r}")
            self.arg_dict[k]._data = _as_nd(v)._data.astype(
                self.arg_dict[k].dtype)
        arg_vals = {n: a._data for n, a in self.arg_dict.items()}
        aux_vals = {n: a._data for n, a in self.aux_dict.items()}
        rng = _rnd.next_key()
        outs, new_aux = self._forward_fn(bool(is_train))(arg_vals, aux_vals, rng)
        self.outputs = [NDArray(o) for o in outs]
        if is_train:
            self._last = (rng, arg_vals, aux_vals)
            for name, val in new_aux.items():
                self.aux_dict[name]._data = val
        if self._monitor_callback is not None:
            for name, o in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, o)
        return self.outputs

    def backward(self, out_grads=None):
        if self._last is None:
            raise MXNetError("backward called before forward(is_train=True)")
        rng, arg_vals, aux_vals = self._last
        bwd = self._backward_fn()
        wrt = self._wrt
        if not wrt:
            return
        import jax.numpy as jnp
        if out_grads is None:
            cts = [jnp.ones(o.shape, o.dtype) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                   for g in out_grads]
        diff_vals = {n: arg_vals[n] for n in wrt}
        fixed_vals = {n: v for n, v in arg_vals.items() if n not in diff_vals}
        grads = bwd(diff_vals, fixed_vals, aux_vals, rng, cts)
        for n in wrt:
            g = grads[n]
            if self._grad_req[n] == "add":
                self.grad_dict[n]._data = self.grad_dict[n]._data + g
            else:
                self.grad_dict[n]._data = g

    # -- misc ---------------------------------------------------------------
    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for n, v in arg_params.items():
            if n in self.arg_dict:
                self.arg_dict[n]._data = _as_nd(v)._data.astype(
                    self.arg_dict[n].dtype)
            elif not allow_extra_params:
                raise MXNetError(f"unknown parameter {n!r}")
        if aux_params:
            for n, v in aux_params.items():
                if n in self.aux_dict:
                    self.aux_dict[n]._data = _as_nd(v)._data.astype(
                        self.aux_dict[n].dtype)
                elif not allow_extra_params:
                    raise MXNetError(f"unknown aux state {n!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new input shapes, keeping parameter arrays whose
        shapes are unchanged (reference executor.py reshape)."""
        shapes = dict(kwargs)
        for n, a in self.arg_dict.items():
            shapes.setdefault(n, a.shape)
        new = Executor.simple_bind(self._symbol, self._ctx,
                                   grad_req=self._grad_req, **{
                                       k: v for k, v in shapes.items()})
        for n, a in self.arg_dict.items():
            if n in new.arg_dict and new.arg_dict[n].shape == a.shape:
                new.arg_dict[n]._data = a._data
        for n, a in self.aux_dict.items():
            if n in new.aux_dict and new.aux_dict[n].shape == a.shape:
                new.aux_dict[n]._data = a._data
        return new

    @staticmethod
    def simple_bind(symbol, ctx=None, grad_req="write", type_dict=None,
                    **shapes):
        """Allocate arrays from inferred shapes and bind
        (reference graph_executor.cc:388 Init, simple_bind path)."""
        from .ndarray import zeros

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        known = {k: tuple(v) for k, v in shapes.items()
                 if not isinstance(v, (str, type, _np.dtype))}
        dtypes = {k: dtype_np(v) for k, v in (type_dict or {}).items()}
        shapes_map, types_map = symbol._run_inference(
            known, dtypes, False, want_types=True)
        unk = [n for n in arg_names + aux_names if shapes_map.get(n) is None]
        if unk:
            raise MXNetError(f"simple_bind: could not infer shapes for {unk}")
        from .base import dtype_name
        args = {n: zeros(shapes_map[n], dtype=dtype_name(types_map[n]))
                for n in arg_names}
        aux = {n: zeros(shapes_map[n], dtype=dtype_name(types_map[n]))
               for n in aux_names}
        return Executor(symbol, ctx, args=args, grad_req=grad_req,
                        aux_states=aux)
