"""mxnet.numpy_extension (`npx`): framework extensions to the numpy
namespace (reference python/mxnet/numpy_extension/ — neural-net ops,
np-semantics switches, device helpers).

The nn ops bridge to the same registered operators the nd/gluon layers use
(ops/nn_ops.py, ops/tensor_ops.py); because registry outputs are
class-preserving, np.ndarray in -> np.ndarray out."""
from __future__ import annotations

from ..base import MXNetError
from ..context import cpu, gpu, num_gpus, tpu  # noqa: F401
from ..ops.registry import get_op, apply_op
from ..numpy.multiarray import _as_np, ndarray  # noqa: F401
from ..util import (is_np_array, is_np_shape, np_array, np_shape,  # noqa: F401
                    reset_np, set_np, set_np_shape, use_np, use_np_shape)

__all__ = ["softmax", "log_softmax", "sigmoid", "relu", "leaky_relu",
           "activation", "fully_connected", "convolution", "pooling",
           "batch_norm", "layer_norm", "dropout", "embedding", "one_hot",
           "pick", "topk", "reshape_like", "arange_like", "gamma",
           "sequence_mask", "seed", "save", "load", "waitall",
           "set_np", "reset_np", "is_np_array", "is_np_shape", "cpu", "gpu",
           "tpu", "num_gpus"]


def _bridge(op_name, *arrays, **params):
    arrs = [_as_np(a) if not isinstance(a, ndarray) else a for a in arrays]
    return apply_op(get_op(op_name), *arrs, **params)


def softmax(data, axis=-1, temperature=None):
    p = {"axis": axis}
    if temperature is not None:
        p["temperature"] = temperature
    return _bridge("softmax", data, **p)


def log_softmax(data, axis=-1):
    return _bridge("log_softmax", data, axis=axis)


def sigmoid(data):
    return _bridge("sigmoid", data)


def relu(data):
    return _bridge("relu", data)


def leaky_relu(data, act_type="leaky", slope=0.25):
    return _bridge("LeakyReLU", data, act_type=act_type, slope=slope)


def activation(data, act_type="relu"):
    return _bridge("Activation", data, act_type=act_type)


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    if bias is None or no_bias:
        return _bridge("FullyConnected", x, weight,
                       num_hidden=num_hidden or weight.shape[0],
                       no_bias=True, flatten=flatten)
    return _bridge("FullyConnected", x, weight, bias,
                   num_hidden=num_hidden or weight.shape[0],
                   no_bias=False, flatten=flatten)


def convolution(data, weight, bias=None, **params):
    if bias is None:
        return _bridge("Convolution", data, weight, no_bias=True, **params)
    return _bridge("Convolution", data, weight, bias, **params)


def pooling(data, **params):
    return _bridge("Pooling", data, **params)


def batch_norm(x, gamma, beta, running_mean, running_var, **params):
    return _bridge("BatchNorm", x, gamma, beta, running_mean, running_var,
                   **params)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _bridge("LayerNorm", data, gamma, beta, axis=axis, eps=eps)


def dropout(data, p=0.5, **params):
    return _bridge("Dropout", data, p=p, **params)


def embedding(data, weight, input_dim=None, output_dim=None, **params):
    return _bridge("Embedding", data, weight,
                   input_dim=input_dim or weight.shape[0],
                   output_dim=output_dim or weight.shape[1], **params)


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _bridge("one_hot", data, depth=depth, on_value=on_value,
                   off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, keepdims=False):
    return _bridge("pick", data, index, axis=axis, keepdims=keepdims)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    return _bridge("topk", data, axis=axis, k=k, ret_typ=ret_typ,
                   is_ascend=is_ascend)


def reshape_like(lhs, rhs):
    from ..numpy import reshape
    return reshape(_as_np(lhs), rhs.shape)


def arange_like(data, start=0.0, step=1.0, axis=None):
    """Reference npx.arange_like: values laid out over data's full shape
    (row-major) when axis is None, else a 1-D ramp of data.shape[axis]."""
    import jax.numpy as jnp
    if axis is None:
        ramp = jnp.arange(data.size, dtype="float32") * step + start
        return ndarray(ramp.reshape(data.shape))
    n = data.shape[axis]
    return ndarray(jnp.arange(n, dtype="float32") * step + start)


def gamma(data):
    return _bridge("gamma", data)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if sequence_length is not None:
        return _bridge("SequenceMask", data, sequence_length,
                       use_sequence_length=True, value=value, axis=axis)
    return _bridge("SequenceMask", data, use_sequence_length=False,
                   value=value, axis=axis)


def seed(s):
    from ..ndarray import random as _r
    _r.seed(s)


def save(fname, arrays):
    from ..ndarray.utils import save as _save
    return _save(fname, arrays)


def load(fname):
    from ..ndarray.utils import load as _load
    out = _load(fname)
    if isinstance(out, dict):
        return {k: _as_np(v) for k, v in out.items()}
    return [_as_np(v) for v in out]


def waitall():
    from ..ndarray import waitall as _w
    return _w()
