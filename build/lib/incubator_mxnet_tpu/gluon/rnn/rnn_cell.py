"""Unfused recurrent cells + modifiers.

Reference: python/mxnet/gluon/rnn/rnn_cell.py — single-step cells
(RNN/LSTM/GRU) with `unroll`, plus Sequential/Bidirectional containers and
Dropout/Zoneout/Residual modifiers. Gate math matches the fused op
(ops/rnn_ops.py) so a cell-unrolled network and the fused layer agree
numerically. `unroll` is a Python loop over steps — under hybridize the
whole unrolled graph compiles into one XLA program.
"""
from __future__ import annotations

from ... import nd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of per-step tensors (reference
    rnn_cell.py _format_sequence)."""
    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        seq = list(inputs)
        batch = seq[0].shape[0]
    else:
        if length is None:
            length = inputs.shape[axis]
        seq = [nd.squeeze(nd.slice_axis(inputs, axis=axis, begin=i, end=i + 1),
                          axis=axis) for i in range(length)]
        batch = inputs.shape[layout.find("N")]
    return seq, axis, batch


def _merge_outputs(outputs, axis):
    return nd.stack(*outputs, axis=axis)


class RecurrentCell(HybridBlock):
    """Base cell (reference rnn_cell.py RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for child in self._children.values():
            if hasattr(child, "reset"):
                child.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if self._modified:
            raise MXNetError("cannot begin_state on a modifier-wrapped cell; "
                             "call it on the outermost cell")
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            info.pop("__layout__", None)
            states.append(func(**info, **kwargs))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        if not isinstance(states, (list, tuple)):
            states = [states]
        return super().__call__(inputs, *states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Reference rnn_cell.py unroll."""
        self.reset()
        seq, axis, batch = _format_sequence(length, inputs, layout, merge_outputs)
        if begin_state is None:
            begin_state = self.begin_state(batch, dtype=seq[0].dtype)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            out, states = self(seq[i], states)
            outputs.append(out)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.SequenceLast(nd.stack(*[s[j] for s in all_states],
                                               axis=0),
                                      valid_length, use_sequence_length=True,
                                      axis=0)
                      for j in range(len(states))]
            outputs = [nd.SequenceMask(
                _merge_outputs(outputs, 0), valid_length,
                use_sequence_length=True, axis=0)]
            merged = nd.swapaxes(outputs[0], dim1=0, dim2=1) if axis == 1 \
                else outputs[0]
            return merged, states
        if merge_outputs is None or merge_outputs:
            return _merge_outputs(outputs, axis), states
        return outputs, states


class _BaseRNNCell(RecurrentCell):
    def __init__(self, hidden_size, gates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = gates
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(ng * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(ng * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)
        for n in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
            self._reg_params[n] = getattr(self, n)

    def infer_shape(self, x, *args):
        self.i2h_weight._infer_shape(
            (self.i2h_weight.shape[0], int(x.shape[-1])))
        for p in (self.h2h_weight, self.i2h_bias, self.h2h_bias):
            if p._deferred_init is not None:
                p._finish_deferred_init()


class RNNCell(_BaseRNNCell):
    """Elman cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, state, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        pre = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size) + \
            F.FullyConnected(state, h2h_weight, h2h_bias,
                             num_hidden=self._hidden_size)
        out = F.Activation(pre, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseRNNCell):
    """LSTM cell, gate order [i, f, g, o] (reference rnn_cell.py LSTMCell,
    matching the fused op / cuDNN layout)."""

    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, h, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        nh = self._hidden_size
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=4 * nh) + \
            F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=4 * nh)
        i, f, g, o = (F.slice_axis(gates, axis=-1, begin=k * nh,
                                   end=(k + 1) * nh) for k in range(4))
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c_new = f * c + i * g
        h_new = o * F.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(_BaseRNNCell):
    """GRU cell, cuDNN linear_before_reset semantics (matches fused op)."""

    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, h, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        nh = self._hidden_size
        xp = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=3 * nh)
        hp = F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=3 * nh)
        xr, xz, xn = (F.slice_axis(xp, axis=-1, begin=k * nh, end=(k + 1) * nh)
                      for k in range(3))
        hr, hz, hn = (F.slice_axis(hp, axis=-1, begin=k * nh, end=(k + 1) * nh)
                      for k in range(3))
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.tanh(xn + r * hn)
        out = (1 - z) * n + z * h
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference rnn_cell.py SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell, str(len(self._children)))

    def state_info(self, batch_size=0):
        info = []
        for cell in self._children.values():
            info.extend(cell.state_info(batch_size))
        return info

    def __call__(self, inputs, states):
        self._counter += 1
        if not isinstance(states, (list, tuple)):
            states = [states]
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        if begin_state is None:
            seq, _, batch = _format_sequence(length, inputs, layout, None)
            begin_state = self.begin_state(batch, dtype=seq[0].dtype)
        p = 0
        states = []
        cells = list(self._children.values())
        for i, cell in enumerate(cells):
            n = len(cell.state_info())
            st = begin_state[p:p + n]
            p += n
            inputs, st = cell.unroll(
                length, inputs, begin_state=st, layout=layout,
                merge_outputs=None if i < len(cells) - 1 else merge_outputs,
                valid_length=valid_length)
            states.extend(st)
        return inputs, states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


HybridSequentialRNNCell = SequentialRNNCell


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        base_cell._modified = True
        self.base_cell = base_cell
        self.register_child(base_cell, "base_cell")

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class DropoutCell(RecurrentCell):
    """Apply dropout to step outputs (reference DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.rate = rate
        self.axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs):
        if self.rate > 0:
            inputs = F.Dropout(inputs, p=self.rate, axes=self.axes)
        return inputs, []

    def __call__(self, inputs, states):
        out, _ = super().__call__(inputs, [])
        if isinstance(out, tuple):
            out = out[0]
        return out, states

    def forward(self, inputs, *states):
        out = self._eager_forward(inputs)
        return out


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        from ... import autograd

        out, next_states = self.base_cell(inputs, states)
        if not autograd.is_training():
            return out, next_states
        po, ps = self.zoneout_outputs, self.zoneout_states

        def mask(rate, like):
            return nd.Dropout(nd.ones_like(like), p=rate, training=True)

        prev = self._prev_output if self._prev_output is not None \
            else nd.zeros_like(out)
        if po:
            m = mask(po, out)
            out = nd.where(m, out, prev)
        if ps:
            next_states = [nd.where(mask(ps, ns), ns, s)
                           for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(ModifierCell):
    """Add the input to the cell output (reference ResidualCell)."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        seq, axis, _ = _format_sequence(length, inputs, layout, True)
        if isinstance(outputs, list):
            outputs = [o + s for o, s in zip(outputs, seq)]
        else:
            outputs = outputs + _merge_outputs(seq, axis)
        return outputs, states


class BidirectionalCell(RecurrentCell):
    """Run two cells over the sequence in opposite directions
    (reference BidirectionalCell; unroll-only)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix=None, params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot run stepwise; use unroll")

    def state_info(self, batch_size=0):
        l, r = self._children["l_cell"], self._children["r_cell"]
        return l.state_info(batch_size) + r.state_info(batch_size)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        seq, axis, batch = _format_sequence(length, inputs, layout, None)
        if begin_state is None:
            begin_state = self.begin_state(batch, dtype=seq[0].dtype)
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(length, seq,
                                        begin_state=begin_state[:nl],
                                        layout="TNC", merge_outputs=False,
                                        valid_length=valid_length)
        # reverse respecting per-sample lengths so padding never leads the
        # reverse pass (reference rnn_cell.py BidirectionalCell uses
        # SequenceReverse with use_sequence_length)
        stacked = nd.stack(*seq, axis=0)
        if valid_length is not None:
            rev_in = nd.SequenceReverse(stacked, valid_length,
                                        use_sequence_length=True, axis=0)
        else:
            rev_in = nd.SequenceReverse(stacked, axis=0)
        rseq = [nd.squeeze(nd.slice_axis(rev_in, axis=0, begin=i, end=i + 1),
                           axis=0) for i in range(length)]
        r_out, r_states = r_cell.unroll(length, rseq,
                                        begin_state=begin_state[nl:],
                                        layout="TNC", merge_outputs=False,
                                        valid_length=valid_length)
        if isinstance(l_out, list):
            r_merged = _merge_outputs(r_out, 0)
        else:
            r_merged = r_out
        if valid_length is not None:
            r_rev = nd.SequenceReverse(r_merged, valid_length,
                                       use_sequence_length=True, axis=0)
        else:
            r_rev = nd.SequenceReverse(r_merged, axis=0)
        l_merged = _merge_outputs(l_out, 0) if isinstance(l_out, list) \
            else l_out
        merged = nd.concat(l_merged, r_rev, dim=-1)
        if axis == 1:
            merged = nd.swapaxes(merged, dim1=0, dim2=1)
        if merge_outputs is False and valid_length is None:
            t_axis = 1 if axis == 1 else 0
            merged = [nd.squeeze(nd.slice_axis(merged, axis=t_axis, begin=i,
                                               end=i + 1), axis=t_axis)
                      for i in range(length)]
        return merged, l_states + r_states
