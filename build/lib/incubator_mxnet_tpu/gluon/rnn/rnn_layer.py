"""RNN/LSTM/GRU layers over the fused RNN op.

Reference: python/mxnet/gluon/rnn/rnn_layer.py — per-(layer, direction)
i2h/h2h parameters concatenated into the fused op's flat cuDNN-layout
vector at forward. Same here: the concat is one XLA fusion, and the fused
op (ops/rnn_ops.py) hoists input projections out of its lax.scan so the
recurrent loop stays MXU-bound.
"""
from __future__ import annotations

from ... import autograd, nd
from ...base import MXNetError
from ...ops.rnn_ops import GATES as _GATES
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout!r}; need TNC or NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _GATES[mode]

        ng, nh = self._gates, hidden_size
        for layer in range(num_layers):
            for d in ["l", "r"][:self._dir]:
                in_sz = input_size if layer == 0 else hidden_size * self._dir
                for conn, sz in (("i2h", in_sz), ("h2h", nh)):
                    w = self.params.get(
                        f"{d}{layer}_{conn}_weight", shape=(ng * nh, sz),
                        init=(i2h_weight_initializer if conn == "i2h"
                              else h2h_weight_initializer),
                        dtype=dtype, allow_deferred_init=True)
                    b = self.params.get(
                        f"{d}{layer}_{conn}_bias", shape=(ng * nh,),
                        init=(i2h_bias_initializer if conn == "i2h"
                              else h2h_bias_initializer),
                        dtype=dtype, allow_deferred_init=True)
                    self._reg_params[f"{d}{layer}_{conn}_weight"] = w
                    self._reg_params[f"{d}{layer}_{conn}_bias"] = b

    def _alias(self):
        # called from Block.__init__ before _mode is assigned
        return getattr(self, "_mode", type(self).__name__.lower())

    def state_info(self, batch_size=0):
        info = [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append(dict(info[0]))
        return info

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial hidden (and cell) state (reference rnn_layer.py
        begin_state)."""
        func = func or nd.zeros
        return [func(shape=i["shape"], **kwargs) for i in
                self.state_info(batch_size)]

    def infer_shape(self, x, *args):
        in_sz = int(x.shape[2] if self._layout == "TNC" else x.shape[-1])
        ng, nh = self._gates, self._hidden_size
        for layer in range(self._num_layers):
            for d in ["l", "r"][:self._dir]:
                sz = in_sz if layer == 0 else nh * self._dir
                self._reg_params[f"{d}{layer}_i2h_weight"]._infer_shape(
                    (ng * nh, sz))
                self._reg_params[f"{d}{layer}_h2h_weight"]._infer_shape(
                    (ng * nh, nh))
                self._reg_params[f"{d}{layer}_i2h_bias"]._infer_shape(
                    (ng * nh,))
                self._reg_params[f"{d}{layer}_h2h_bias"]._infer_shape(
                    (ng * nh,))

    def forward(self, inputs, states=None):
        self._num_inputs = 1
        skip_states = states is None
        if skip_states:
            if not hasattr(inputs, "shape"):
                raise MXNetError(
                    "symbolic trace requires explicit begin_state()")
            batch = inputs.shape[self._layout.index("N")]
            states = self.begin_state(batch, dtype=inputs.dtype)
        if not isinstance(states, (list, tuple)):
            states = [states]
        out = super().forward(inputs, *states)
        outputs, *out_states = out
        return outputs if skip_states else (outputs, out_states)

    def hybrid_forward(self, F, inputs, *states, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)

        # flat cuDNN-layout vector: all weights, then all biases
        # (reference rnn-inl.h GetRnnParamSize; _rnn_param_concat)
        order = []
        for layer in range(self._num_layers):
            for d in ["l", "r"][:self._dir]:
                order.append(f"{d}{layer}_i2h_weight")
                order.append(f"{d}{layer}_h2h_weight")
        bias_order = []
        for layer in range(self._num_layers):
            for d in ["l", "r"][:self._dir]:
                bias_order.append(f"{d}{layer}_i2h_bias")
                bias_order.append(f"{d}{layer}_h2h_bias")
        flat = F.concat(*[F.reshape(params[k], shape=(-1,))
                          for k in order + bias_order], dim=0)

        rnn_args = [inputs, flat, states[0]]
        if self._mode == "lstm":
            rnn_args.append(states[1])
        res = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True)
        if self._mode == "lstm":
            outputs, h, c = res
            out_states = [h, c]
        else:
            outputs, h = res
            out_states = [h]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        return tuple([outputs] + out_states)

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, layout={self._layout!r}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Elman RNN with relu/tanh (reference rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(f"rnn_{activation}", hidden_size, num_layers,
                         layout, dropout, bidirectional, input_size, **kwargs)


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)


class GRU(_RNNLayer):
    """Multi-layer GRU, cuDNN gate semantics (reference rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)
