"""Gluon losses.

Covers the reference set (python/mxnet/gluon/loss.py: L1/L2/SigmoidBCE/
SoftmaxCE/KL/CTC/Huber/Hinge/SquaredHinge/Logistic/Triplet/Cosine) with a
different internal shape: every loss implements `_unreduced` returning the
per-element loss, and the base class owns weighting + per-sample reduction.
Numerically-stable formulations are built on one `_softplus` helper
(log(1+e^x) = relu(x) + log1p(e^-|x|)) instead of softrelu activations.
"""
from __future__ import annotations

from .. import nd
from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L1Loss", "L2Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "CosineEmbeddingLoss"]


def _softplus(F, x):
    """Stable log(1 + e^x)."""
    return F.relu(x) + F.log(1.0 + F.exp(-F.abs(x)))


def _match(label, pred):
    """View the label with the prediction's shape (layouts always agree up
    to a trailing singleton in this API)."""
    return label.reshape(pred.shape)


class Loss(HybridBlock):
    """Base: subclasses implement _unreduced(F, *args) -> elementwise loss;
    the base applies the constructor weight, the per-call sample_weight, and
    the mean over every non-batch axis."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _finish(self, F, loss, sample_weight, reduce=True):
        if sample_weight is not None:
            loss = F.broadcast_mul(loss, sample_weight)
        if self._weight is not None:
            loss = loss * self._weight
        if reduce:
            loss = F.mean(loss, axis=self._batch_axis, exclude=True)
        return loss

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        return self._finish(F, self._unreduced(F, pred, label), sample_weight)

    def _unreduced(self, F, pred, label):
        raise NotImplementedError


class L1Loss(Loss):
    """mean |pred - label|."""

    def _unreduced(self, F, pred, label):
        return F.abs(pred - _match(label, pred))


class L2Loss(Loss):
    """mean (pred - label)^2 / 2 (the reference's 1/2 convention)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _unreduced(self, F, pred, label):
        d = pred - _match(label, pred)
        return F.square(d) * 0.5


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE on logits (default) or on probabilities (from_sigmoid=True).

    Logit form: softplus(x) - x*y, with the optional pos_weight rescaling
    the positive-class term as in the reference.
    """

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        y = _match(label, pred)
        if self._from_sigmoid:
            eps = 1e-12
            pos_term = F.log(pred + eps) * y
            if pos_weight is not None:
                pos_term = F.broadcast_mul(pos_term, pos_weight)
            loss = -(pos_term + F.log(1.0 - pred + eps) * (1.0 - y))
        elif pos_weight is None:
            loss = _softplus(F, pred) - pred * y
        else:
            # rescale only the y=1 branch: loss = (1 + (pw-1) y) softplus(-x)
            #                                     + (1-y) x  [- x*0 terms]
            w = 1.0 + F.broadcast_mul(pos_weight - 1.0, y)
            loss = w * _softplus(F, -pred) + (1.0 - y) * pred
        return self._finish(F, loss, sample_weight)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Cross entropy over an axis; sparse integer labels by default."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def _unreduced(self, F, pred, label):
        logp = pred if self._from_logits else F.log_softmax(pred,
                                                            axis=self._axis)
        if self._sparse_label:
            return -F.pick(logp, label, axis=self._axis, keepdims=True)
        return -F.sum(logp * _match(label, logp), axis=self._axis,
                      keepdims=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """KL(label || pred); pred is log-probabilities when from_logits=True
    (the default, matching the reference)."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def _unreduced(self, F, pred, label):
        logq = pred if self._from_logits else F.log_softmax(pred,
                                                            axis=self._axis)
        return label * (F.log(label + 1e-12) - logq)


class CTCLoss(Loss):
    """Connectionist temporal classification, blank = last class
    (reference loss.py CTCLoss over the warp-ctc op)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"CTC layout must be NTC or TNC, got {layout}")
        if label_layout not in ("NT", "TN"):
            raise MXNetError(f"CTC label_layout must be NT or TN")
        self._layout = layout
        self._label_layout = label_layout
        super().__init__(weight, label_layout.find("N"), **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._label_layout == "TN":
            label = F.swapaxes(label, dim1=0, dim2=1)
        loss = F.CTCLoss(pred, label, pred_lengths, label_lengths,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         blank_label="last")
        return self._finish(F, loss, sample_weight, reduce=False)


class HuberLoss(Loss):
    """Quadratic within rho of the target, linear outside."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def _unreduced(self, F, pred, label):
        err = F.abs(pred - _match(label, pred))
        quad = F.square(err) * (0.5 / self._rho)
        return F.where(err > self._rho, err - 0.5 * self._rho, quad)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def _unreduced(self, F, pred, label):
        return F.relu(self._margin - pred * _match(label, pred))


class SquaredHingeLoss(HingeLoss):
    def _unreduced(self, F, pred, label):
        return F.square(super()._unreduced(F, pred, label))


class LogisticLoss(Loss):
    """BCE on logits with labels in {-1,1} ('signed', default) or {0,1}
    ('binary')."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format}")
        self._label_format = label_format

    def _unreduced(self, F, pred, label):
        y = _match(label, pred)
        if self._label_format == "signed":
            y = (y + 1.0) * 0.5
        return _softplus(F, pred) - pred * y


class TripletLoss(Loss):
    """relu(margin + d(pred, pos) - d(pred, neg)), squared-L2 distances."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        gap = F.square(pred - _match(positive, pred)) - \
            F.square(pred - _match(negative, pred))
        per_sample = F.relu(F.sum(gap, axis=self._batch_axis, exclude=True) +
                            self._margin)
        return self._finish(F, per_sample, sample_weight, reduce=False)


class CosineEmbeddingLoss(Loss):
    """1 - cos(a,b) for label 1; relu(cos(a,b) - margin) for label -1."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        a = input1.reshape((input1.shape[0], -1))
        b = input2.reshape((input2.shape[0], -1))
        cos = F.sum(a * b, axis=-1) / (F.norm(a, axis=-1) *
                                       F.norm(b, axis=-1) + 1e-12)
        loss = F.where(label.reshape((-1,)) == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        return self._finish(F, loss, sample_weight, reduce=False)
