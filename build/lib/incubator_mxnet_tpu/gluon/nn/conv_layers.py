"""Gluon convolution / pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py: Conv1D-3D(+Transpose),
Max/Avg/GlobalMax/GlobalAvgPool1D-3D, ReflectionPad2D.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 transpose=False, output_padding=0, **kwargs):
        super().__init__(**kwargs)
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._transpose = transpose
        self._output_padding = _tup(output_padding, ndim)
        self.act_type = activation
        if transpose:
            wshape = (in_channels, channels // groups) + tuple(kernel_size)
        else:
            wshape = (channels, in_channels // groups if in_channels else 0) + \
                tuple(kernel_size)
        self.weight = self.params.get("weight", shape=wshape,
                                      init=weight_initializer,
                                      allow_deferred_init=True)
        self._reg_params["weight"] = self.weight
        if use_bias:
            self.bias = self.params.get("bias", shape=(channels,),
                                        init=bias_initializer,
                                        allow_deferred_init=True)
            self._reg_params["bias"] = self.bias
        else:
            self.bias = None

    def infer_shape(self, x, *args):
        ci = int(x.shape[1])
        if self._transpose:
            self.weight._infer_shape((ci, self._channels // self._groups) +
                                     tuple(self._kernel))
        else:
            self.weight._infer_shape((self._channels, ci // self._groups) +
                                     tuple(self._kernel))

    def hybrid_forward(self, F, x, weight, bias=None):
        if self._transpose:
            out = F.Deconvolution(x, weight, bias, kernel=self._kernel,
                                  stride=self._strides, dilate=self._dilation,
                                  pad=self._padding, adj=self._output_padding,
                                  num_filter=self._channels,
                                  num_group=self._groups, no_bias=bias is None)
        else:
            out = F.Convolution(x, weight, bias, kernel=self._kernel,
                                stride=self._strides, dilate=self._dilation,
                                pad=self._padding, num_filter=self._channels,
                                num_group=self._groups, no_bias=bias is None)
        if self.act_type:
            out = F.Activation(out, act_type=self.act_type)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         dilation, groups, layout, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         dilation, groups, layout, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         dilation, groups, layout, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         dilation, groups, layout, transpose=True,
                         output_padding=output_padding, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         dilation, groups, layout, transpose=True,
                         output_padding=output_padding, **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         dilation, groups, layout, transpose=True,
                         output_padding=output_padding, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 ceil_mode=False, count_include_pad=True, ndim=2, **kwargs):
        super().__init__(**kwargs)
        self._ndim = ndim
        self._kernel = pool_size
        self._stride = strides if strides is not None else pool_size
        self._pad = padding
        self._global = global_pool
        self._pool_type = pool_type
        self._convention = "full" if ceil_mode else "valid"
        self._count_include_pad = count_include_pad

    def hybrid_forward(self, F, x):
        # spatial rank comes from the layer config, not the input, so the
        # same code traces symbolically (Symbols have no static ndim)
        ndim = self._ndim
        return F.Pooling(x, kernel=_tup(self._kernel, ndim),
                         stride=_tup(self._stride, ndim),
                         pad=_tup(self._pad, ndim), pool_type=self._pool_type,
                         global_pool=self._global,
                         pooling_convention=self._convention,
                         count_include_pad=self._count_include_pad)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", ceil_mode,
                         ndim=1, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", ceil_mode,
                         ndim=2, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", ceil_mode,
                         ndim=3, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", ceil_mode,
                         count_include_pad, ndim=1, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", ceil_mode,
                         count_include_pad, ndim=2, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", ceil_mode,
                         count_include_pad, ndim=3, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(1, None, 0, True, "max", ndim=1, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "max", ndim=2, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "max", ndim=3, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(1, None, 0, True, "avg", ndim=1, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "avg", ndim=2, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "avg", ndim=3, **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
