"""Gluon utilities (reference python/mxnet/gluon/utils.py, 470 LoC:
split_data/split_and_load/clip_global_norm/download)."""
from __future__ import annotations

import os

import numpy as _np

from .. import nd
from ..base import MXNetError

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Reference gluon/utils.py split_data."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(f"cannot evenly split axis of size {size} into "
                         f"{num_slice} slices")
    step = size // num_slice
    if batch_axis == 0:
        return [data[i * step:(i + 1) * step] for i in range(num_slice)]
    return [nd.slice_axis(data, axis=batch_axis, begin=i * step,
                          end=(i + 1) * step) for i in range(num_slice)]


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch across contexts (reference gluon/utils.py).

    On a TPU mesh prefer parallel.shard_batch — sharding over copies; this
    keeps the multi-Context API for parity."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Reference gluon/utils.py clip_global_norm."""
    assert len(arrays) > 0
    total = 0.0
    for a in arrays:
        n = float(nd.norm(a).asscalar())
        total += n * n
    total = total ** 0.5
    if check_isfinite and not _np.isfinite(total):
        return total
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._data = (a * scale)._data
    return total


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Reference gluon/utils.py download. This environment has no egress;
    only file:// URLs and existing local paths work."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil
        src = url[7:]
        if not os.path.exists(src):
            raise MXNetError(f"download source not found: {url}")
        shutil.copyfile(src, fname)
        return fname
    raise MXNetError("network downloads unavailable (zero-egress environment); "
                     f"place the file at {fname} manually")
