"""gluon.contrib (reference python/mxnet/gluon/contrib/): estimator fit
loop + event handlers, extra nn layers, conv/variational RNN cells."""
from . import estimator, nn, rnn

__all__ = ["estimator", "nn", "rnn"]
