"""gluon.contrib.nn layers.

Reference: python/mxnet/gluon/contrib/nn/basic_layers.py — Concurrent,
HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm, PixelShuffle
1D/2D/3D. TPU notes inline where the design diverges.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..nn.basic_layers import BatchNorm, Sequential, HybridSequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs
    (reference basic_layers.py Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        outs = [block(x) for block in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (reference HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def _eager_forward(self, x, *args):
        outs = [block(x) for block in self._children.values()]
        return nd.concat(*outs, dim=self.axis)

    def hybrid_forward(self, F, x):
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through (reference Identity) — useful in Concurrent branches."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose gradient is row_sparse (reference SparseEmbedding;
    here backed by the row_sparse grad path of the Embedding op with
    sparse_grad=True — see ndarray/sparse.py)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": True}
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      init=weight_initializer, dtype=dtype,
                                      grad_stype="row_sparse")
        self._reg_params["weight"] = self.weight

    def forward(self, x):
        return nd.Embedding(x, self.weight.data(), **self._kwargs)

    def __repr__(self):
        return (f"SparseEmbedding({self._kwargs['input_dim']} -> "
                f"{self._kwargs['output_dim']})")


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm.

    Reference: src/operator/contrib/sync_batch_norm-inl.h:56-197 (key-based
    barrier + cross-GPU reduce) and gluon.contrib.nn.SyncBatchNorm
    (num_devices). TPU-native design: inside a pjit'd train step the batch
    axis is a mesh axis, so XLA's batch-norm statistics ARE global — the
    barrier machinery is unnecessary. This subclass exists for API parity
    and for eager multi-device loops, where stats are computed over the
    full (already gathered) batch.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        self._factors = (int(factor),) * ndim if isinstance(factor, int) \
            else tuple(int(f) for f in factor)
        assert len(self._factors) == ndim

    def __repr__(self):
        return f"{type(self).__name__}(factors={self._factors})"


class PixelShuffle1D(_PixelShuffle):
    """(N, C*f, W) -> (N, C, W*f) (reference PixelShuffle1D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def hybrid_forward(self, F, x):
        f, = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f, 0))   # (N, C, f, W)
        x = F.transpose(x, axes=(0, 1, 3, 2))       # (N, C, W, f)
        return F.reshape(x, shape=(0, 0, -3))       # (N, C, W*f)


class PixelShuffle2D(_PixelShuffle):
    """(N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2) (reference PixelShuffle2D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))  # N C H f1 W f2
        return F.reshape(x, shape=(0, 0, -3, -3))


class PixelShuffle3D(_PixelShuffle):
    """(N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, 0, -4, f2, f3, 0, 0, 0))
        # N C f1 f2 f3 D H W -> N C D f1 H f2 W f3
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(0, 0, -3, -3, -3))
