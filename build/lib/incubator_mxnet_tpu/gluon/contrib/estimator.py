"""Estimator: high-level fit loop with event handlers.

Reference: python/mxnet/gluon/contrib/estimator/estimator.py +
event_handler.py — Estimator.fit drives train/val epochs and dispatches
to handlers at train/epoch/batch boundaries; handlers cover logging,
metrics, validation, checkpointing, and early stopping.
"""
from __future__ import annotations

import copy
import logging
import time

from ... import autograd, metric as _metric, ndarray as nd
from ...base import MXNetError
from ..trainer import Trainer

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator, batch):
        pass


class BatchEnd:
    def batch_end(self, estimator, batch, pred, label, loss):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch / max_batch (reference event_handler.py
    StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch

    def train_begin(self, estimator):
        self.current_batch = 0
        self.current_epoch = 0
        if self.max_batch == 0 or self.max_epoch == 0:
            estimator.stop_training = True

    def batch_end(self, estimator, batch, pred, label, loss):
        self.current_batch += 1
        if self.max_batch is not None and self.current_batch >= self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator):
        self.current_epoch += 1
        if self.max_epoch is not None and self.current_epoch >= self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Update train metrics every batch, reset per epoch."""

    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, batch, pred, label, loss):
        for m in self.metrics:
            if isinstance(m, _metric.Loss):
                m.update(0, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, EpochEnd):
    """Run evaluation on val_data every `epoch_period` epochs."""

    def __init__(self, val_data, eval_fn, epoch_period=1):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period

    def train_begin(self, estimator):
        self._epoch = 0

    def epoch_end(self, estimator):
        self._epoch += 1
        if self._epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Log throughput + metric values (reference LoggingHandler;
    Speedometer-style img/s)."""

    def __init__(self, log_interval="epoch", metrics=None,
                 logger=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.logger = logger or logging.getLogger("estimator")
        self.batch_index = 0

    def train_begin(self, estimator):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator):
        self.logger.info("Training done in %.1fs",
                         time.time() - self.train_start)

    def epoch_begin(self, estimator):
        self.epoch_start = time.time()
        self.batch_index = 0
        self.samples = 0

    def batch_end(self, estimator, batch, pred, label, loss):
        self.batch_index += 1
        self.samples += label.shape[0] if hasattr(label, "shape") else 0
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msg = " ".join(f"{n}={v:.4f}" for n, v in
                           (m.get() for m in self.metrics))
            self.logger.info("[batch %d] %s", self.batch_index, msg)

    def epoch_end(self, estimator):
        dt = time.time() - self.epoch_start
        speed = self.samples / dt if dt > 0 else 0.0
        msg = " ".join(f"{n}={v:.4f}" for n, v in
                       (m.get() for m in self.metrics))
        self.logger.info("epoch done: %.1f samples/s %s", speed, msg)


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save params (+trainer states) every epoch_period epochs
    (reference CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", epoch_period=1,
                 max_checkpoints=5, save_best=False, monitor=None,
                 mode="min"):
        import os
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.epoch_period = epoch_period
        self.max_checkpoints = max_checkpoints
        self.save_best = save_best
        self.monitor = monitor
        self.mode = mode
        self.best = None
        self.saved = []
        os.makedirs(model_dir, exist_ok=True)

    def train_begin(self, estimator):
        self._epoch = 0

    def epoch_end(self, estimator):
        import os
        self._epoch += 1
        if self._epoch % self.epoch_period:
            return
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-{self._epoch:04d}.params")
        estimator.net.save_parameters(path)
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            if val != val:  # NaN must not poison best-checkpoint tracking
                return
            better = self.best is None or \
                (val < self.best if self.mode == "min" else val > self.best)
            if better:
                self.best = val
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stop when the monitored metric stops improving
    (reference EarlyStoppingHandler)."""

    def __init__(self, monitor, mode="min", patience=2, min_delta=0.0):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta

    def train_begin(self, estimator):
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def epoch_end(self, estimator):
        _, val = self.monitor.get()
        if val != val:  # NaN
            return
        improved = self.best is None or \
            (val < self.best - self.min_delta if self.mode == "min"
             else val > self.best + self.min_delta)
        if improved:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                estimator.stop_training = True


class Estimator:
    """High-level train/eval driver (reference estimator.py Estimator)."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None):
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics or [_metric.Accuracy()]
        if not isinstance(self.train_metrics, (list, tuple)):
            self.train_metrics = [self.train_metrics]
        self.train_metrics = list(self.train_metrics)
        self.train_loss_metric = _metric.Loss("train_loss")
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.stop_training = False
        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        self.val_loss_metric = _metric.Loss("val_loss")

    def evaluate(self, val_data):
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric.reset()
        for batch in val_data:
            x, y = batch
            pred = self.net(x)
            loss = self.loss(pred, y)
            self.val_loss_metric.update(0, loss)
            for m in self.val_metrics:
                m.update(y, pred)
        return {n: v for n, v in (m.get() for m in
                                  self.val_metrics + [self.val_loss_metric])}

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None):
        if epochs is None and batches is None:
            raise MXNetError("fit needs epochs or batches")
        # order matters at epoch_end: ValidationHandler must refresh the
        # val metrics BEFORE user handlers (early stopping / best
        # checkpoint) read them; StoppingHandler runs last
        handlers = [MetricHandler(
            self.train_metrics + [self.train_loss_metric])]
        if val_data is not None:
            handlers.append(ValidationHandler(val_data, self.evaluate))
        handlers.extend(event_handlers or [])
        handlers.append(StoppingHandler(epochs, batches))

        def dispatch(kind, *args):
            for h in handlers:
                fn = getattr(h, kind, None)
                if fn is not None and isinstance(h, _HOOK_TYPES[kind]):
                    fn(self, *args)

        self.stop_training = False
        dispatch("train_begin")
        epoch_cap = epochs if epochs is not None else 2 ** 31
        for _ in range(epoch_cap):
            if self.stop_training:
                break
            dispatch("epoch_begin")
            for i, (x, y) in enumerate(train_data):
                dispatch("batch_begin", i)
                with autograd.record():
                    pred = self.net(x)
                    loss = self.loss(pred, y)
                    mean_loss = loss.mean()
                mean_loss.backward()
                bs = x.shape[0] if hasattr(x, "shape") else 1
                self.trainer.step(bs)
                dispatch("batch_end", i, pred, y, loss)
                if self.stop_training:
                    break
            dispatch("epoch_end")
        dispatch("train_end")
        return self


_HOOK_TYPES = {
    "train_begin": TrainBegin, "train_end": TrainEnd,
    "epoch_begin": EpochBegin, "epoch_end": EpochEnd,
    "batch_begin": BatchBegin, "batch_end": BatchEnd,
}
