"""gluon.contrib.rnn cells.

Reference: python/mxnet/gluon/contrib/rnn/rnn_cell.py
(VariationalDropoutCell, LSTMPCell) and conv_rnn_cell.py
(Conv1D/2D/3D RNN/LSTM/GRU cells).
"""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..rnn.rnn_cell import ModifierCell, RecurrentCell

__all__ = ["VariationalDropoutCell", "LSTMPCell", "Conv1DRNNCell",
           "Conv2DRNNCell", "Conv3DRNNCell", "Conv1DLSTMCell",
           "Conv2DLSTMCell", "Conv3DLSTMCell", "Conv1DGRUCell",
           "Conv2DGRUCell", "Conv3DGRUCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational dropout (Gal & Ghahramani): ONE dropout mask per unroll,
    reused at every timestep, applied to inputs/states/outputs.
    Reference: gluon/contrib/rnn/rnn_cell.py VariationalDropoutCell."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.reset()

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, p, like):
        """Bernoulli keep-mask scaled by 1/(1-p), same shape as `like`."""
        keep = nd.uniform(low=0.0, high=1.0, shape=like.shape) >= p
        return keep.astype(like.dtype) / (1.0 - p)

    def __call__(self, inputs, states):
        from ... import autograd
        training = autograd.is_training() or autograd.is_recording()
        if training and self.drop_inputs > 0:
            if self._input_mask is None:
                self._input_mask = self._mask(self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        if training and self.drop_states > 0:
            if self._state_mask is None:
                self._state_mask = self._mask(self.drop_states, states[0])
            states = [states[0] * self._state_mask] + list(states[1:])
        out, nstates = self.base_cell(inputs, states)
        if training and self.drop_outputs > 0:
            if self._output_mask is None:
                self._output_mask = self._mask(self.drop_outputs, out)
            out = out * self._output_mask
        return out, nstates

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()  # fresh masks each unroll
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              valid_length=valid_length)

    def __repr__(self):
        return (f"VariationalDropoutCell(in={self.drop_inputs}, "
                f"state={self.drop_states}, out={self.drop_outputs})")


class LSTMPCell(RecurrentCell):
    """LSTM with a projection layer on the hidden state
    (reference gluon/contrib/rnn/rnn_cell.py LSTMPCell; arXiv:1402.1128).
    The recurrent input is the PROJECTED state, so h2h_weight is
    (4*hidden, projection) and h2r_weight projects h -> r."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 h2r_weight_initializer=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        nh = hidden_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * nh, input_size),
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * nh, projection_size),
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * nh,), init="zeros",
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * nh,), init="zeros",
            allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, nh),
            init=h2r_weight_initializer, allow_deferred_init=True)
        for n in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias",
                  "h2r_weight"):
            self._reg_params[n] = getattr(self, n)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def infer_shape(self, x, *args):
        self.i2h_weight._infer_shape(
            (self.i2h_weight.shape[0], int(x.shape[-1])))
        for p in (self.h2h_weight, self.i2h_bias, self.h2h_bias,
                  self.h2r_weight):
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def hybrid_forward(self, F, inputs, r, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias, h2r_weight):
        nh = self._hidden_size
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=4 * nh) + \
            F.FullyConnected(r, h2h_weight, h2h_bias, num_hidden=4 * nh)
        i, f, g, o = (F.slice_axis(gates, axis=-1, begin=k * nh,
                                   end=(k + 1) * nh) for k in range(4))
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c_new = f * c + i * g
        h_new = o * F.tanh(c_new)
        r_new = F.FullyConnected(h_new, h2r_weight, no_bias=True,
                                 num_hidden=self._projection_size)
        return r_new, [r_new, c_new]


class _ConvRNNBase(RecurrentCell):
    """Shared machinery for convolutional recurrent cells (reference
    conv_rnn_cell.py _BaseConvRNNCell): i2h and h2h are convolutions over
    (N, C, spatial...) instead of dense layers."""

    def __init__(self, input_shape, hidden_channels, gates,
                 i2h_kernel, h2h_kernel, i2h_pad=None, conv_ndim=2,
                 activation="tanh", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, spatial...)
        self._hidden_channels = hidden_channels
        self._conv_ndim = conv_ndim
        self._activation = activation
        tup = lambda v: (v,) * conv_ndim if isinstance(v, int) else tuple(v)
        self._i2h_kernel = tup(i2h_kernel)
        self._h2h_kernel = tup(h2h_kernel)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError("h2h_kernel must be odd to preserve the "
                                 f"state's spatial shape, got {k}")
        self._i2h_pad = tup(i2h_pad) if i2h_pad is not None else \
            tuple(k // 2 for k in self._i2h_kernel)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)

        in_ch = self._input_shape[0]
        ng = gates
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(ng * hidden_channels, in_ch) + self._i2h_kernel,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(ng * hidden_channels, hidden_channels) + self._h2h_kernel,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_channels,), init="zeros",
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_channels,), init="zeros",
            allow_deferred_init=True)
        for n in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
            self._reg_params[n] = getattr(self, n)

    def state_info(self, batch_size=0):
        spatial = tuple(
            (s + 2 * p - k) + 1
            for s, p, k in zip(self._input_shape[1:], self._i2h_pad,
                               self._i2h_kernel))
        shape = (batch_size, self._hidden_channels) + spatial
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._conv_ndim:]}
                for _ in range(self._n_states)]

    def _convs(self, F, inputs, state, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias, gates):
        nf = gates * self._hidden_channels
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=nf)
        h2h = F.Convolution(state, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=nf)
        return i2h, h2h

    def _split(self, F, x, n):
        return F.SliceChannel(x, num_outputs=n, axis=1)


class _ConvRNNCell(_ConvRNNBase):
    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 conv_ndim, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, 1, i2h_kernel,
                         h2h_kernel, conv_ndim=conv_ndim,
                         activation=activation, **kwargs)

    def hybrid_forward(self, F, inputs, state, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, state, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias, 1)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class _ConvLSTMCell(_ConvRNNBase):
    _n_states = 2

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 conv_ndim, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, 4, i2h_kernel,
                         h2h_kernel, conv_ndim=conv_ndim,
                         activation=activation, **kwargs)

    def hybrid_forward(self, F, inputs, h, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, h, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias, 4)
        gates = i2h + h2h
        i, f, g, o = self._split(F, gates, 4)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.Activation(g, act_type=self._activation)
        c_new = f * c + i * g
        h_new = o * F.Activation(c_new, act_type=self._activation)
        return h_new, [h_new, c_new]


class _ConvGRUCell(_ConvRNNBase):
    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 conv_ndim, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, 3, i2h_kernel,
                         h2h_kernel, conv_ndim=conv_ndim,
                         activation=activation, **kwargs)

    def hybrid_forward(self, F, inputs, h, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h, h2h = self._convs(F, inputs, h, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias, 3)
        xr, xz, xn = self._split(F, i2h, 3)
        hr, hz, hn = self._split(F, h2h, 3)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.Activation(xn + r * hn, act_type=self._activation)
        out = (1 - z) * n + z * h
        return out, [out]


def _mk(base, ndim, alias):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, **kwargs):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, conv_ndim=ndim, **kwargs)

        def _alias(self):
            return alias
    Cell.__name__ = Cell.__qualname__ = alias
    return Cell


Conv1DRNNCell = _mk(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _mk(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _mk(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _mk(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _mk(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _mk(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _mk(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _mk(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _mk(_ConvGRUCell, 3, "Conv3DGRUCell")
