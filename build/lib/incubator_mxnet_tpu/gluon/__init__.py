"""Gluon: imperative + hybridizable neural network API
(reference python/mxnet/gluon/)."""
from . import nn
from . import loss
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, Parameter, ParameterDict
from .trainer import Trainer


def __getattr__(name):
    import importlib
    lazy = {"rnn": ".rnn", "data": ".data", "model_zoo": ".model_zoo",
            "contrib": ".contrib", "utils": ".utils"}
    if name in lazy:
        m = importlib.import_module(lazy[name], __name__)
        globals()[name] = m
        return m
    raise AttributeError(f"module 'gluon' has no attribute {name!r}")
