"""Load reference-format .params files into zoo nets by name mapping.

A reference-trained artifact (gluon save_parameters: the ndarray save
wire with structure-dotted keys like `features.0.weight`) cannot load
through Block.load_parameters here because the two implementations nest
blocks differently, so the dotted paths disagree even though both nets
are the same canonical architecture.

The mapping key insight: `_collect_params_with_prefix` walks children in
registration order on BOTH sides, and a canonical architecture declares
its layers in topological order — so the k-th parameter OF EACH ROLE
(conv/fc weight, bias, BN gamma/beta/running stats) on one side is the
k-th of that role on the other. The loader therefore matches by (role
sequence, shape), which is invariant to how the blocks are nested, and
verifies every shape before any assignment (all-or-nothing).

Reference counterpart: python/mxnet/gluon/model_zoo/model_store.py +
block.load_parameters — which get this mapping for free by being the
same implementation.
"""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["load_reference_parameters", "param_role"]

_ROLE_SUFFIXES = ("weight", "bias", "gamma", "beta", "running_mean",
                  "running_var", "moving_mean", "moving_var")


def param_role(name):
    """Map a parameter name (dotted or underscored) to its role. The two
    BN running-stat spellings (reference layers use running_*, symbol-era
    files moving_*) collapse to one role each."""
    leaf = name.rsplit(".", 1)[-1].rsplit("_", 1)[-1]
    full = name.rsplit(".", 1)[-1]
    for suf in _ROLE_SUFFIXES:
        if full.endswith(suf):
            role = suf.replace("moving_", "running_")
            return role
    raise MXNetError(f"cannot classify parameter {name!r} "
                     f"(leaf {leaf!r}) into a role")


def load_reference_parameters(net, filename, strict=True):
    """Load a reference-format .params file into `net` by role-sequence
    mapping. Returns {our_name: their_name} for audit."""
    from ...ndarray.utils import load as nd_load

    loaded = nd_load(filename)
    # strip the arg:/aux: prefixes the symbol-era save wrote
    theirs = {}
    for k, v in loaded.items():
        if k.startswith(("arg:", "aux:")):
            k = k[4:]
        theirs[k] = v

    ours = net._collect_params_with_prefix()

    def by_role(names):
        seq = {}
        for n in names:
            seq.setdefault(param_role(n), []).append(n)
        return seq

    # insertion order of dicts preserves the collection (= registration /
    # file) order on both sides
    their_seq = by_role(theirs.keys())
    our_seq = by_role(ours.keys())

    mapping = {}
    for role, our_names in our_seq.items():
        their_names = their_seq.get(role, [])
        if len(their_names) != len(our_names):
            if strict:
                raise MXNetError(
                    f"role {role!r}: file has {len(their_names)} "
                    f"parameters, net needs {len(our_names)}")
            continue
        for o, t in zip(our_names, their_names):
            o_shape = tuple(ours[o].shape or ())
            t_shape = tuple(theirs[t].shape)
            # deferred-init parameters have 0-dims: adopt the file's shape
            if all(s > 0 for s in o_shape) and o_shape and \
                    o_shape != t_shape:
                raise MXNetError(
                    f"shape mismatch mapping {t!r} -> {o!r}: "
                    f"{t_shape} vs {o_shape}")
            mapping[o] = t
    extra = set(theirs) - {t for t in mapping.values()}
    if strict and extra:
        raise MXNetError(f"file has unmapped parameters: {sorted(extra)[:5]}")

    # every known shape verified: assign (set_data adopts the file's
    # shape for deferred-init parameters)
    for o, t in mapping.items():
        ours[o].set_data(theirs[t])
    return mapping


def load_pretrained(net, name, root=None):
    """Shared pretrained=True path for every zoo factory (reference
    python/mxnet/gluon/model_zoo/vision/*.py: each factory calls
    get_model_file + load_parameters). Resolves `name` through the
    sha1-verified model_store cache and loads the reference-format
    .params via the role-sequence compat mapper, so pretrained=True can
    never silently return random weights."""
    from .model_store import get_model_file
    load_reference_parameters(net, get_model_file(name, root=root))
    return net
