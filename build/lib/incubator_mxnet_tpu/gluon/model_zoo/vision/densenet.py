"""DenseNet 121/161/169/201.

Same architectures as the reference (python/mxnet/gluon/model_zoo/vision/
densenet.py), restructured: the dense block is ONE HybridBlock that loops
its bottleneck layers and carries the concatenation internally, rather than
a sequential of per-layer concat blocks.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "get_densenet"]

# depth -> (stem width, growth rate k, units per dense block)
_SPECS = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
}


class _DenseBlock(HybridBlock):
    """`units` bottleneck layers (BN-relu-1x1 -> BN-relu-3x3, each emitting
    k channels) with the running feature concat held in the loop."""

    def __init__(self, units, growth, bn_size=4, dropout=0, **kwargs):
        super().__init__(**kwargs)
        self.norms1 = nn.HybridSequential(prefix="")
        self.convs1 = nn.HybridSequential(prefix="")
        self.norms2 = nn.HybridSequential(prefix="")
        self.convs2 = nn.HybridSequential(prefix="")
        self._dropout = dropout
        for _ in range(units):
            self.norms1.add(nn.BatchNorm())
            self.convs1.add(nn.Conv2D(bn_size * growth, 1, use_bias=False))
            self.norms2.add(nn.BatchNorm())
            self.convs2.add(nn.Conv2D(growth, 3, padding=1, use_bias=False))

    def hybrid_forward(self, F, x):
        for n1, c1, n2, c2 in zip(self.norms1, self.convs1,
                                  self.norms2, self.convs2):
            y = c1(F.relu(n1(x)))
            y = c2(F.relu(n2(y)))
            if self._dropout:
                y = F.Dropout(y, p=self._dropout)
            x = F.concat(x, y, dim=1)
        return x


class _Transition(HybridBlock):
    """BN-relu-1x1 halving channels, then 2x2 average pool."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.norm = nn.BatchNorm()
        self.conv = nn.Conv2D(channels, 1, use_bias=False)
        self.pool = nn.AvgPool2D(2, 2)

    def hybrid_forward(self, F, x):
        return self.pool(self.conv(F.relu(self.norm(x))))


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(nn.Conv2D(num_init_features, 7, strides=2,
                                    padding=3, use_bias=False))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(3, 2, 1))
        width = num_init_features
        for i, units in enumerate(block_config):
            self.features.add(_DenseBlock(units, growth_rate, bn_size, dropout))
            width += units * growth_rate
            if i + 1 < len(block_config):
                width //= 2
                self.features.add(_Transition(width))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_densenet(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    if num_layers not in _SPECS:
        raise MXNetError(f"no densenet spec for depth {num_layers}")
    stem, growth, blocks = _SPECS[num_layers]
    net = DenseNet(stem, growth, blocks, **kwargs)
    if pretrained:
        from ..compat import load_pretrained
        load_pretrained(net, f"densenet{num_layers}", root=root)
    return net


def _ctor(depth):
    def f(**kwargs):
        return get_densenet(depth, **kwargs)
    f.__name__ = f"densenet{depth}"
    return f


densenet121, densenet161, densenet169, densenet201 = \
    (_ctor(d) for d in (121, 161, 169, 201))
