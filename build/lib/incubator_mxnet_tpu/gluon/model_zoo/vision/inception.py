"""Inception V3, spec-driven.

Capability parity with the reference's Inception3 (python/mxnet/gluon/
model_zoo/vision/inception.py), built differently: the whole network is a
declarative table. Every inception module is a tuple of branch *trees* —
a branch is a sequence of primitives (`C` conv-bn-relu specs and pooling
atoms), and the V3 "E" modules' forked tails are expressed with a `Split`
node instead of a dedicated block class. One generic `_Mixed` block
interprets the trees; nothing is hand-assembled per module type.

Architecture constants (channel counts, kernel/stride/padding) are the
published Inception-V3 topology and therefore match any implementation.
"""
from __future__ import annotations

from collections import namedtuple

from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]

# branch primitives -----------------------------------------------------
C = namedtuple("C", "ch k s p")         # conv(ch, kernel) + BN + relu
C.__new__.__defaults__ = (1, 0)          # s=1, p=0
AVG3 = "avg3"                            # 3x3 stride-1 avg pool, pad 1
MAX3 = "max3"                            # 3x3 stride-2 max pool
Split = namedtuple("Split", "head tails")  # run head, concat tails


def _module_A(pool_ch):
    return ((C(64, 1),),
            (C(48, 1), C(64, 5, p=2)),
            (C(64, 1), C(96, 3, p=1), C(96, 3, p=1)),
            (AVG3, C(pool_ch, 1)))


def _module_B():
    return ((C(384, 3, s=2),),
            (C(64, 1), C(96, 3, p=1), C(96, 3, s=2)),
            (MAX3,))


def _module_C(ch7):
    return ((C(192, 1),),
            (C(ch7, 1), C(ch7, (1, 7), p=(0, 3)), C(192, (7, 1), p=(3, 0))),
            (C(ch7, 1), C(ch7, (7, 1), p=(3, 0)), C(ch7, (1, 7), p=(0, 3)),
             C(ch7, (7, 1), p=(3, 0)), C(192, (1, 7), p=(0, 3))),
            (AVG3, C(192, 1)))


def _module_D():
    return ((C(192, 1), C(320, 3, s=2)),
            (C(192, 1), C(192, (1, 7), p=(0, 3)), C(192, (7, 1), p=(3, 0)),
             C(192, 3, s=2)),
            (MAX3,))


def _module_E():
    fork13 = ((C(384, (1, 3), p=(0, 1)),), (C(384, (3, 1), p=(1, 0)),))
    return ((C(320, 1),),
            Split((C(384, 1),), fork13),
            Split((C(448, 1), C(384, 3, p=1)), fork13),
            (AVG3, C(192, 1)))


# stem + module sequence (published V3 layout)
_STEM = (C(32, 3, s=2), C(32, 3), C(64, 3, p=1), MAX3,
         C(80, 1), C(192, 3), MAX3)
_MODULES = (_module_A(32), _module_A(64), _module_A(64),
            _module_B(),
            _module_C(128), _module_C(160), _module_C(160), _module_C(192),
            _module_D(),
            _module_E(), _module_E())


class _ConvUnit(HybridBlock):
    """conv -> BatchNorm(eps=1e-3) -> relu, bias-free."""

    def __init__(self, spec, **kwargs):
        super().__init__(**kwargs)
        self.conv = nn.Conv2D(spec.ch, spec.k, strides=spec.s,
                              padding=spec.p, use_bias=False)
        self.bn = nn.BatchNorm(epsilon=0.001)

    def hybrid_forward(self, F, x):
        return F.relu(self.bn(self.conv(x)))


def _build_seq(atoms, prefix):
    seq = nn.HybridSequential(prefix=prefix)
    for atom in atoms:
        if atom == AVG3:
            seq.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
        elif atom == MAX3:
            seq.add(nn.MaxPool2D(pool_size=3, strides=2))
        else:
            seq.add(_ConvUnit(atom))
    return seq


class _Mixed(HybridBlock):
    """Interpret one inception-module spec: run every branch tree on the
    input and concatenate along channels. A Split branch runs its head
    then both tails (each concatenated in place, V3 'E' style)."""

    def __init__(self, branches, prefix=None, **kwargs):
        super().__init__(prefix=prefix, **kwargs)
        self._plan = []
        for bi, br in enumerate(branches):
            if isinstance(br, Split):
                head = _build_seq(br.head, f"b{bi}_")
                tails = [_build_seq(t, f"b{bi}t{ti}_")
                         for ti, t in enumerate(br.tails)]
                self.register_child(head)
                for t in tails:
                    self.register_child(t)
                self._plan.append(("split", head, tails))
            else:
                seq = _build_seq(br, f"b{bi}_")
                self.register_child(seq)
                self._plan.append(("seq", seq, None))

    def hybrid_forward(self, F, x):
        outs = []
        for kind, head, tails in self._plan:
            if kind == "seq":
                outs.append(head(x))
            else:
                mid = head(x)
                outs.append(F.concat(*[t(mid) for t in tails], dim=1))
        return F.concat(*outs, dim=1)


class Inception3(HybridBlock):
    """Inception V3 over 299x299 inputs (reference inception.py:147)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(_build_seq(_STEM, "stem_"))
        for mi, spec in enumerate(_MODULES):
            self.features.add(_Mixed(spec, prefix=f"mixed{mi}_"))
        self.features.add(nn.AvgPool2D(pool_size=8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(F.flatten(self.features(x)))


def inception_v3(pretrained=False, ctx=None, root=None, **kwargs):
    """Reference inception_v3() factory (vision/inception.py)."""
    net = Inception3(**kwargs)
    if pretrained:
        from ..compat import load_pretrained
        load_pretrained(net, "inceptionv3", root=root)
    return net
