"""AlexNet, table-driven.

Same architecture the reference ships (python/mxnet/gluon/model_zoo/vision/
alexnet.py), expressed as a conv-spec table + classifier loop instead of an
inline layer list.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["AlexNet", "alexnet"]

# (channels, kernel, stride, pad, pool_after)
_CONV_TABLE = [
    (64, 11, 4, 2, True),
    (192, 5, 1, 2, True),
    (384, 3, 1, 1, False),
    (256, 3, 1, 1, False),
    (256, 3, 1, 1, True),
]


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, dropout=0.5, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        for ch, k, s, p, pool in _CONV_TABLE:
            self.features.add(nn.Conv2D(ch, k, strides=s, padding=p,
                                        activation="relu"))
            if pool:
                self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Flatten())
        for _ in range(2):
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(dropout))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, ctx=None, root=None, **kwargs):
    """Reference alexnet() factory (vision/alexnet.py)."""
    net = AlexNet(**kwargs)
    if pretrained:
        from ..compat import load_pretrained
        load_pretrained(net, "alexnet", root=root)
    return net
