"""MobileNet v1 / v2, paper-table driven.

Same architectures as the reference (python/mxnet/gluon/model_zoo/vision/
mobilenet.py) but generated from the published stage tables: v1 from a
(out_channels, stride) list of depthwise-separable pairs, v2 from the
(expansion t, out c, repeats n, stride s) table of the MobileNetV2 paper.

Depthwise convs are grouped Conv2D (groups == channels); XLA lowers grouped
convolutions natively, so no hand-written depthwise kernels are needed
(the reference carries depthwise_convolution_tf.cuh for CUDA).
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "MobileNetV2", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25",
           "get_mobilenet", "get_mobilenet_v2"]

# v1: (out_channels, stride) per depthwise-separable pair
_V1_TABLE = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
             (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
             (1024, 1)]

# v2: (expansion t, out channels c, repeats n, first stride s) — paper tab.2
_V2_TABLE = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
             (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]


class _ConvBN(HybridBlock):
    """conv -> BN -> optional (relu | relu6)."""

    def __init__(self, channels, kernel=1, stride=1, groups=1, act="relu",
                 **kwargs):
        super().__init__(**kwargs)
        self.conv = nn.Conv2D(channels, kernel, strides=stride,
                              padding=kernel // 2, groups=groups,
                              use_bias=False)
        self.bn = nn.BatchNorm()
        self._act = act

    def hybrid_forward(self, F, x):
        y = self.bn(self.conv(x))
        if self._act == "relu":
            y = F.relu(y)
        elif self._act == "relu6":
            y = F.clip(y, a_min=0.0, a_max=6.0)
        return y


class _InvertedResidual(HybridBlock):
    """MobileNetV2 block: 1x1 expand (t*) -> 3x3 depthwise -> 1x1 linear
    project, identity shortcut when shapes allow."""

    def __init__(self, in_ch, out_ch, t, stride, **kwargs):
        super().__init__(**kwargs)
        self._identity = (stride == 1 and in_ch == out_ch)
        mid = in_ch * t
        self.layers = nn.HybridSequential(prefix="")
        # the reference LinearBottleneck keeps the 1x1 expansion even at t=1
        # (python/mxnet/gluon/model_zoo/vision/mobilenet.py _add_conv chain),
        # so parameter layouts line up with reference-exported weights
        self.layers.add(_ConvBN(mid, 1, act="relu6"))
        self.layers.add(_ConvBN(mid, 3, stride, groups=mid, act="relu6"))
        self.layers.add(_ConvBN(out_ch, 1, act=None))

    def hybrid_forward(self, F, x):
        y = self.layers(x)
        return x + y if self._identity else y


class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        scale = lambda c: max(1, int(c * multiplier))
        self.features = nn.HybridSequential(prefix="")
        self.features.add(_ConvBN(scale(32), 3, 2))
        prev = scale(32)
        for out, stride in _V1_TABLE:
            # depthwise 3x3 over prev channels, then 1x1 pointwise to out
            self.features.add(_ConvBN(prev, 3, stride, groups=prev))
            self.features.add(_ConvBN(scale(out), 1))
            prev = scale(out)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        scale = lambda c: max(1, int(c * multiplier))
        self.features = nn.HybridSequential(prefix="features_")
        prev = scale(32)
        self.features.add(_ConvBN(prev, 3, 2, act="relu6"))
        for t, c, n, s in _V2_TABLE:
            for i in range(n):
                out = scale(c)
                self.features.add(_InvertedResidual(prev, out, t,
                                                    s if i == 0 else 1))
                prev = out
        head = 1280 if multiplier <= 1.0 else scale(1280)
        self.features.add(_ConvBN(head, 1, act="relu6"))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.HybridSequential(prefix="output_")
        self.output.add(nn.Conv2D(classes, 1, use_bias=False))
        self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_mobilenet(multiplier, pretrained=False, ctx=None, root=None, **kwargs):
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        from ..compat import load_pretrained
        load_pretrained(net, f"mobilenet{float(multiplier)}", root=root)
    return net


def get_mobilenet_v2(multiplier, pretrained=False, ctx=None, root=None,
                     **kwargs):
    net = MobileNetV2(multiplier, **kwargs)
    if pretrained:
        from ..compat import load_pretrained
        load_pretrained(net, f"mobilenetv2_{float(multiplier)}", root=root)
    return net


def _ctor(factory, mult, name):
    def f(**kwargs):
        return factory(mult, **kwargs)
    f.__name__ = name
    return f


mobilenet1_0 = _ctor(get_mobilenet, 1.0, "mobilenet1_0")
mobilenet0_75 = _ctor(get_mobilenet, 0.75, "mobilenet0_75")
mobilenet0_5 = _ctor(get_mobilenet, 0.5, "mobilenet0_5")
mobilenet0_25 = _ctor(get_mobilenet, 0.25, "mobilenet0_25")
mobilenet_v2_1_0 = _ctor(get_mobilenet_v2, 1.0, "mobilenet_v2_1_0")
mobilenet_v2_0_75 = _ctor(get_mobilenet_v2, 0.75, "mobilenet_v2_0_75")
mobilenet_v2_0_5 = _ctor(get_mobilenet_v2, 0.5, "mobilenet_v2_0_5")
mobilenet_v2_0_25 = _ctor(get_mobilenet_v2, 0.25, "mobilenet_v2_0_25")
