"""SqueezeNet 1.0/1.1, stage-spec driven.

Same fire-module architectures as the reference (python/mxnet/gluon/
model_zoo/vision/squeezenet.py), but the two versions are data: a layout
list of fire widths and pool markers, consumed by one builder.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1", "get_squeezenet"]


class _Fire(HybridBlock):
    """squeeze 1x1 -> relu -> parallel expand 1x1 / expand 3x3 -> concat."""

    def __init__(self, squeeze, expand, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
        self.left = nn.Conv2D(expand, 1, activation="relu")
        self.right = nn.Conv2D(expand, 3, padding=1, activation="relu")

    def hybrid_forward(self, F, x):
        y = self.squeeze(x)
        return F.concat(self.left(y), self.right(y), dim=1)


# layout entries: "P" = 3x3/2 ceil max-pool, int n = fire(squeeze=n,
# expand=4n per branch — the published ratio), tuple = stem conv
_LAYOUTS = {
    "1.0": [(96, 7, 2), "P", 16, 16, 32, "P", 32, 48, 48, 64, "P", 64],
    "1.1": [(64, 3, 2), "P", 16, 16, "P", 32, 32, "P", 48, 48, 64, 64],
}


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in _LAYOUTS:
            raise MXNetError(f"squeezenet version {version!r} not in "
                             f"{sorted(_LAYOUTS)}")
        self.features = nn.HybridSequential(prefix="")
        for entry in _LAYOUTS[version]:
            if entry == "P":
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            elif isinstance(entry, tuple):
                ch, k, s = entry
                self.features.add(nn.Conv2D(ch, k, strides=s,
                                            activation="relu"))
            else:
                self.features.add(_Fire(entry, entry * 4))
        self.features.add(nn.Dropout(0.5))
        # fully-convolutional classifier head
        self.output = nn.HybridSequential(prefix="")
        self.output.add(nn.Conv2D(classes, 1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_squeezenet(version, pretrained=False, ctx=None, root=None, **kwargs):
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        from ..compat import load_pretrained
        load_pretrained(net, f"squeezenet{version}", root=root)
    return net


def squeezenet1_0(**kwargs):
    return get_squeezenet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return get_squeezenet("1.1", **kwargs)
