"""Gluon model zoo (reference python/mxnet/gluon/model_zoo/)."""
from . import model_store, vision
from .compat import load_reference_parameters
from .model_store import get_model_file, purge
from .vision import get_model
