"""Datasets (reference python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import os

import numpy as _np

from ... import nd
from ...base import MXNetError

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]

# Set True inside DataLoader worker processes (dataloader._worker_init):
# workers must stay jax-free — a forked child touching the parent's XLA
# client deadlocks — so datasets store HOST (numpy) arrays and only wrap
# into device-backed NDArrays on access in the main process.
IN_WORKER = False


def _maybe_nd(a, dtype=None):
    if IN_WORKER or not isinstance(a, _np.ndarray):
        return a
    return nd.array(a, dtype=dtype)


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def take(self, count):
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(*items):
            if len(items) == 1:
                return fn(items[0])
            return (fn(items[0]),) + items[1:]
        return self.transform(lambda *items: first(*items), lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset, fn):
        self._dataset = dataset
        self._fn = fn

    def __len__(self):
        return len(self._dataset)

    def __getitem__(self, idx):
        item = self._dataset[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class ArrayDataset(Dataset):
    """Zip of arrays/datasets (reference dataset.py ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            if len(a) != self._length:
                raise MXNetError("all arrays must have the same length")
            self._data.append(a)
        # main-process access uses device-resident columns (one upload,
        # device-side indexing); numpy copies only materialize when the
        # dataset is pickled to workers (__getstate__)
        self._nd_cache = [a if isinstance(a, nd.NDArray) else None
                          for a in self._data]

    def __getstate__(self):
        # ship HOST arrays to workers: device handles don't pickle and
        # workers must stay jax-free
        host = [a.asnumpy() if isinstance(a, nd.NDArray) else a
                for a in self._data]
        return {"_length": self._length, "_data": host,
                "_nd_cache": [None] * len(host)}

    def __len__(self):
        return self._length

    def _one(self, col, idx):
        if IN_WORKER:
            return self._data[col][idx]
        cache = self._nd_cache[col]
        if cache is None and isinstance(self._data[col], _np.ndarray) \
                and self._data[col].dtype != _np.object_:
            cache = self._nd_cache[col] = nd.array(self._data[col])
        if cache is not None:
            return cache[idx]
        # list / ragged columns: wrap each item on access
        return _maybe_nd(self._data[col][idx])

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._one(0, idx)
        return tuple(self._one(c, idx) for c in range(len(self._data)))


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference dataset.py RecordFileDataset)."""

    def __init__(self, filename):
        from ... import recordio
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
