"""Index samplers for DataLoader.

Reference surface: python/mxnet/gluon/data/sampler.py (Sequential/Random/
Batch). Written generator-first: every sampler is an iterable of indices,
BatchSampler chunks any sampler lazily with keep/discard/rollover tail
policies.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]


class Sampler:
    """Iterable over dataset indices."""

    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    """start, start+1, ..., start+length-1."""

    def __init__(self, length, start=0):
        self._range = range(start, start + length)

    def __iter__(self):
        yield from self._range

    def __len__(self):
        return len(self._range)


class RandomSampler(Sampler):
    """A fresh uniform permutation per epoch."""

    def __init__(self, length):
        self._length = length

    def __iter__(self):
        for i in _np.random.permutation(self._length):
            yield int(i)

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """Chunk `sampler` into lists of batch_size indices.

    last_batch: 'keep' yields the short tail, 'discard' drops it,
    'rollover' prepends it to the next epoch.
    """

    _POLICIES = ("keep", "discard", "rollover")

    def __init__(self, sampler, batch_size, last_batch="keep"):
        if last_batch not in self._POLICIES:
            raise ValueError(f"last_batch must be one of {self._POLICIES}, "
                             f"got {last_batch!r}")
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._carry = []

    def __iter__(self):
        batch = self._carry
        self._carry = []
        for idx in self._sampler:
            batch.append(idx)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if not batch:
            return
        if self._last_batch == "keep":
            yield batch
        elif self._last_batch == "rollover":
            self._carry = batch

    def __len__(self):
        n = len(self._sampler)
        if self._last_batch == "keep":
            return -(-n // self._batch_size)
        if self._last_batch == "discard":
            return n // self._batch_size
        return (n + len(self._carry)) // self._batch_size
