"""Vision transforms (reference python/mxnet/gluon/data/vision/transforms.py:
Compose/Cast/ToTensor/Normalize/Resize/CenterCrop/RandomResizedCrop/
RandomFlipLeftRight/...)."""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from .... import nd
from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential


class Compose(Sequential):
    """Reference transforms.py Compose."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference transforms.py)."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = nd.array(_np.asarray(self._mean, _np.float32).reshape(-1, 1, 1))
        std = nd.array(_np.asarray(self._std, _np.float32).reshape(-1, 1, 1))
        return (x - mean) / std


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        from ....image.image import imresize, resize_short
        if self._keep:
            return resize_short(x, min(self._size), self._interpolation)
        return imresize(x, self._size[0], self._size[1], self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._interpolation = interpolation

    def forward(self, x):
        from ....image.image import center_crop
        return center_crop(x, self._size, self._interpolation)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._args = (size if isinstance(size, (tuple, list)) else (size, size),
                      scale, ratio, interpolation)

    def forward(self, x):
        from ....image.image import random_size_crop
        return random_size_crop(x, *self._args)[0]


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._pad = pad
        self._interpolation = interpolation

    def forward(self, x):
        from ....image.image import random_crop
        if self._pad:
            arr = _np.pad(x.asnumpy(),
                          [(self._pad, self._pad), (self._pad, self._pad), (0, 0)],
                          mode="constant")
            x = nd.array(arr, dtype="uint8")
        return random_crop(x, self._size, self._interpolation)[0]


class RandomFlipLeftRight(HybridBlock):
    def hybrid_forward(self, F, x):
        if _pyrandom.random() < 0.5:
            return F.reverse(x, axis=1 if x.ndim == 3 else 2)
        return x


class RandomFlipTopBottom(HybridBlock):
    def hybrid_forward(self, F, x):
        if _pyrandom.random() < 0.5:
            return F.reverse(x, axis=0 if x.ndim == 3 else 1)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._b, self._b)
        return x.astype("float32") * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        from ....image.image import ContrastJitterAug
        return ContrastJitterAug(self._c)(x.astype("float32"))


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        from ....image.image import SaturationJitterAug
        return SaturationJitterAug(self._s)(x.astype("float32"))


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        from ....image.image import HueJitterAug
        return HueJitterAug(self._h)(x.astype("float32"))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        from ....image.image import ColorJitterAug
        self._aug = ColorJitterAug(brightness, contrast, saturation)
        self._hue = hue

    def forward(self, x):
        from ....image.image import HueJitterAug
        x = self._aug(x.astype("float32"))
        if self._hue:
            x = HueJitterAug(self._hue)(x)
        return x


class RandomLighting(Block):
    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        from ....image.image import LightingAug
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        return LightingAug(self._alpha, eigval, eigvec)(x.astype("float32"))
