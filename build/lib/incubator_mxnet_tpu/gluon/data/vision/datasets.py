"""Vision datasets (reference python/mxnet/gluon/data/vision/datasets.py:
MNIST/FashionMNIST/CIFAR10/CIFAR100/ImageRecordDataset/ImageFolderDataset).

Zero-egress environment: datasets read from local files (`root` dir); the
standard MNIST idx / CIFAR binary formats are parsed natively. A deterministic
synthetic fallback (`synthetic=True`) exists so examples/benchmarks run
without the real archives.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as _np

from .... import nd
from ....base import MXNetError
from ..dataset import Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform, synthetic=False):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._synthetic = synthetic
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        # host (numpy) storage for picklability; main-process access goes
        # through a lazily-built device-resident copy (one upload, indexed
        # on device); workers stay on numpy (dataset.IN_WORKER — jax is
        # not fork/multi-client safe)
        from .. import dataset as _ds
        if _ds.IN_WORKER:
            data = self._data[idx]
        else:
            if getattr(self, "_data_nd", None) is None:
                self._data_nd = nd.array(self._data)
            data = self._data_nd[idx]
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(data, label)
        return data, label

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_data_nd", None)       # device handles don't pickle
        return state

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (reference datasets.py MNIST; idx-ubyte format)."""

    _N_CLASS = 10
    _SHAPE = (28, 28, 1)

    def __init__(self, root="~/.mxtpu/datasets/mnist", train=True,
                 transform=None, synthetic=None):
        self._train_files = ("train-images-idx3-ubyte.gz",
                             "train-labels-idx1-ubyte.gz")
        self._test_files = ("t10k-images-idx3-ubyte.gz",
                            "t10k-labels-idx1-ubyte.gz")
        if synthetic is None:
            synthetic = not self._files_exist(root, train)
        super().__init__(root, train, transform, synthetic)

    def _files_exist(self, root, train):
        files = self._train_files if train else self._test_files
        root = os.path.expanduser(root)
        return all(os.path.exists(os.path.join(root, f)) or
                   os.path.exists(os.path.join(root, f[:-3])) for f in files)

    def _get_data(self):
        if self._synthetic:
            n = 6000 if self._train else 1000
            rng = _np.random.RandomState(42 if self._train else 43)
            labels = rng.randint(0, self._N_CLASS, n).astype(_np.int32)
            base = rng.rand(n, *self._SHAPE) * 0.1
            imgs = ((base + labels[:, None, None, None] / self._N_CLASS) *
                    255).astype(_np.uint8)
            self._data = imgs
            self._label = labels
            return
        imgf, lblf = self._train_files if self._train else self._test_files
        self._label = self._read_idx(os.path.join(self._root, lblf))
        data = self._read_idx(os.path.join(self._root, imgf))
        self._data = data.reshape(-1, 28, 28, 1)

    @staticmethod
    def _read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        if not os.path.exists(path) and path.endswith(".gz"):
            path = path[:-3]
            opener = open
        with opener(path, "rb") as f:
            raw = f.read()
        magic = struct.unpack(">I", raw[:4])[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, raw[4:4 + 4 * ndim])
        return _np.frombuffer(raw[4 + 4 * ndim:],
                              dtype=_np.uint8).reshape(dims).astype(
            _np.int32 if ndim == 1 else _np.uint8)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxtpu/datasets/fashion-mnist", train=True,
                 transform=None, synthetic=None):
        super().__init__(root, train, transform, synthetic)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 binary format (reference datasets.py CIFAR10)."""

    _N_CLASS = 10
    _SHAPE = (32, 32, 3)

    def __init__(self, root="~/.mxtpu/datasets/cifar10", train=True,
                 transform=None, synthetic=None, fine_label=False):
        self._fine_label = fine_label
        if synthetic is None:
            synthetic = not os.path.exists(os.path.expanduser(root))
        super().__init__(root, train, transform, synthetic)

    def _get_data(self):
        if self._synthetic:
            n = 5000 if self._train else 1000
            rng = _np.random.RandomState(44 if self._train else 45)
            labels = rng.randint(0, self._N_CLASS, n).astype(_np.int32)
            imgs = ((rng.rand(n, *self._SHAPE) * 0.2 +
                     labels[:, None, None, None] / self._N_CLASS) * 255
                    ).astype(_np.uint8)
            self._data = imgs
            self._label = labels
            return
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] if self._train \
            else ["test_batch.bin"]
        data, label = [], []
        for fname in files:
            with open(os.path.join(self._root, fname), "rb") as f:
                raw = _np.frombuffer(f.read(), _np.uint8).reshape(-1, 3073)
            label.append(raw[:, 0].astype(_np.int32))
            data.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        self._data = _np.concatenate(data)
        self._label = _np.concatenate(label)


class CIFAR100(CIFAR10):
    _N_CLASS = 100

    def __init__(self, root="~/.mxtpu/datasets/cifar100", fine_label=False,
                 train=True, transform=None, synthetic=None):
        super().__init__(root, train, transform, synthetic, fine_label)


class ImageRecordDataset(Dataset):
    """Dataset over packed image records (reference datasets.py
    ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import recordio
        from ....image.image import imdecode
        record = self._record[idx]
        header, img = recordio.unpack(record)
        img = imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, nd.array(_np.atleast_1d(label)) if not _np.isscalar(label) \
            else (img, label)


class ImageFolderDataset(Dataset):
    """folder/label/img.jpg layout (reference datasets.py ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png")):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from ....image.image import imread
        img = imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
