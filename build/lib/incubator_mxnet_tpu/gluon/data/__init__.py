"""Gluon data API (reference python/mxnet/gluon/data/)."""
from .dataset import ArrayDataset, Dataset, RecordFileDataset, SimpleDataset
from .dataloader import DataLoader, default_batchify_fn
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler


def __getattr__(name):
    if name == "vision":
        import importlib
        m = importlib.import_module(".vision", __name__)
        globals()[name] = m
        return m
    raise AttributeError(name)
