"""Gluon Parameter / ParameterDict.

Reference: python/mxnet/gluon/parameter.py (1,029 LoC): `Parameter:47`
(deferred alloc, grad_req:142, per-ctx copies list_ctx:605, _reduce:381),
`ParameterDict`.

TPU-native redesign: the reference replicates each parameter per GPU context
and all-reduces gradients across copies. Here a parameter owns ONE jax-backed
NDArray whose jax.sharding spec covers any number of devices — replication and
partitioning are sharding annotations, not copies (see parallel/). The
deferred-init dance (shape unknown until first forward) is kept because the
Gluon UX depends on it.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from .. import autograd, initializer, nd
from ..base import MXNetError
from ..context import Context, cpu, current_context

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape was known (reference parameter.py:40)."""


def _shape_known(shape):
    return shape is not None and all(int(s) > 0 for s in shape)


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None
        self._deferred_init = None  # (init, ctx, default_init)
        self._sharding = None  # optional jax.sharding spec (set by parallel/)

    # -- properties ---------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._data.attach_grad(req, stype=self._grad_stype)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    # -- init ---------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Allocate + fill (reference parameter.py initialize). If shape is
        unknown, stash a deferred init executed at first forward."""
        default_init = default_init or initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if not _shape_known(self.shape):
            if not self.allow_deferred_init:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has unknown shape {self.shape} and "
                    f"allow_deferred_init=False")
            self._deferred_init = (init, ctx, default_init)
            return
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        import jax
        ctx = ctx if isinstance(ctx, Context) or ctx is None else \
            (ctx[0] if isinstance(ctx, (list, tuple)) and ctx else None)
        # ensure_compile_time_eval: deferred init may fire while a hybridize
        # trace is being built; parameters must be real device arrays, not
        # tracers of that trace.
        with jax.ensure_compile_time_eval():
            arr = nd.zeros(self.shape, dtype=self.dtype, ctx=ctx)
            filler = init or self.init or default_init
            if isinstance(filler, str):
                filler = initializer.create(filler)
            desc = initializer.InitDesc(self.name)
            with autograd.pause():
                filler(desc, arr)
        self._data = arr
        self._deferred_init = None
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req, stype=self._grad_stype)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                f"Parameter {self.name} was not initialized (call "
                f".initialize() or net.initialize())")
        if not _shape_known(self.shape):
            raise DeferredInitializationError(
                f"Parameter {self.name} shape still unknown: {self.shape}")
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _infer_shape(self, partial_shape):
        """Fill unknown (0) dims from an inferred shape, then finish deferred
        init (called by layers on first forward)."""
        if self.shape is None:
            self.shape = tuple(partial_shape)
        else:
            new = []
            for have, got in zip(self.shape, partial_shape):
                if have and int(have) > 0:
                    if int(got) > 0 and int(got) != int(have):
                        raise MXNetError(
                            f"{self.name}: inferred shape {partial_shape} "
                            f"incompatible with declared {self.shape}")
                    new.append(have)
                else:
                    new.append(got)
            self.shape = tuple(new)
        if self._deferred_init is not None and _shape_known(self.shape):
            self._finish_deferred_init()

    # -- access -------------------------------------------------------------
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} deferred-initialized; run a forward "
                    f"pass (or set shape) first")
            raise MXNetError(f"Parameter {self.name} not initialized; call "
                             f".initialize()")
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        d = self.data()
        if d._grad is None:
            raise MXNetError(f"Parameter {self.name} has grad_req='null'")
        return d._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self.data().context] if self._data is not None else []

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            self._data.zero_grad()

    def set_data(self, data):
        if self._data is None:
            if not _shape_known(self.shape) and hasattr(data, "shape"):
                self.shape = tuple(data.shape)
            self._finish_init(initializer.Constant(0.0), None, None)
        src = data if isinstance(data, nd.NDArray) else nd.array(data)
        self._data._data = src.astype(self.dtype)._data if str(src.dtype) != str(self.dtype) \
            else src._data

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(
                ctx[0] if isinstance(ctx, (list, tuple)) else ctx)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = self._data.astype(dtype)
            if had_grad:
                self._data.attach_grad(self._grad_req,
                                       stype=self._grad_stype)

    def var(self):
        from .. import symbol
        return symbol.var(self.name, shape=self.shape, dtype=self.dtype)


class Constant(Parameter):
    """Non-differentiable constant parameter (reference parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, nd.NDArray):
            value = nd.array(value)
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype), init=None,
                         differentiable=False)
        self.init = _ConstInit(value)


class _ConstInit(initializer.Initializer):
    def __init__(self, value):
        super().__init__()
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value

    _init_default = _init_weight


class ParameterDict:
    """Prefix-scoped name->Parameter mapping (reference parameter.py
    ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        body = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{body}\n)"

    def get(self, name, **kwargs):
        """Create-or-retrieve `prefix+name` (reference parameter.py get)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None and param.shape is not None:
                    param._infer_shape_compat(v) if hasattr(param, "_infer_shape_compat") else None
        return param

    def get_constant(self, name, value=None):
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant {full} and no value given")
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        init = init or initializer.Uniform()
        for p in self.values():
            p.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for p in self.values():
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = p.data()
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd.load(filename)
        if not isinstance(loaded, dict):
            raise MXNetError("params file does not contain a name->array map")
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self.items():
            if name in loaded:
                p._infer_shape(loaded[name].shape)
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing from {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"extra parameters in {filename}: {sorted(extra)}")
