"""Network visualization (reference python/mxnet/visualization.py, 427 LoC):
`print_summary` layer/param table and `plot_network` graphviz rendering."""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Text summary of a symbol graph (reference visualization.py
    print_summary): layer name/type, output shape, params, inputs."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if shape is not None:
        _, out_shapes, _ = symbol.infer_shape(**shape)
        internals = symbol.get_internals()
        _, int_shapes, _ = internals.infer_shape(**shape)
        shape_by_out = dict(zip(internals.list_outputs(), int_shapes))
    else:
        shape_by_out = {}

    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {h[0] for h in conf["heads"]}
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    lines = ["_" * line_length, _row(fields, positions), "=" * line_length]
    total_params = 0

    input_names = set(shape or ())  # user-bound tensors are inputs, not params

    def param_count(node):
        # parameters are the null inputs of this node (weights/biases)
        count = 0
        for ip in node["inputs"]:
            inode = nodes[ip[0]]
            if inode["op"] == "null" and not inode["name"].endswith("label") \
                    and inode["name"] not in input_names \
                    and inode["name"] != "data":
                shp = shape_by_out.get(inode["name"])
                if shp:
                    n = 1
                    for s in shp:
                        n *= s
                    count += n
        return count

    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        name = f"{node['name']} ({node['op']})"
        out_shape = shape_by_out.get(f"{node['name']}_output", "")
        prev = ", ".join(nodes[ip[0]]["name"] for ip in node["inputs"]
                         if nodes[ip[0]]["op"] != "null")
        n_params = param_count(node)
        total_params += n_params
        lines.append(_row([name, str(out_shape), str(n_params), prev],
                          positions))
        lines.append("_" * line_length)
    lines.append(f"Total params: {total_params}")
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out


def _row(fields, positions):
    line = ""
    for f, p in zip(fields, positions):
        line = (line + str(f))[:p].ljust(p)
    return line


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz Digraph of the symbol (reference visualization.py
    plot_network). Requires the `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError("plot_network requires the graphviz package") from e

    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title, format=save_format)
    node_attrs = {"shape": "box", "fixedsize": "false", **(node_attrs or {})}

    def is_param(n):
        return n["op"] == "null" and n["name"] != "data" and \
            not n["name"].endswith("label")

    for i, node in enumerate(nodes):
        if hide_weights and is_param(node):
            continue
        label = node["name"] if node["op"] == "null" else \
            f"{node['op']}\n{node['name']}"
        dot.node(str(i), label=label, **node_attrs)
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for ip in node["inputs"]:
            src = nodes[ip[0]]
            if hide_weights and is_param(src):
                continue
            dot.edge(str(ip[0]), str(i))
    return dot
