"""Engine control surface (reference python/mxnet/engine.py, 75 LoC).

The reference exposes `bulk(size)` to batch engine ops and reduce dispatch
overhead (MXEngineSetBulkSize). XLA's async runtime already pipelines
dispatch, so bulking is a no-op here — the context manager is kept so
reference code runs unchanged, and `set_bulk_size` returns the previous
value like the C API did.
"""
from __future__ import annotations

from contextlib import contextmanager

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = 0


def set_bulk_size(size):
    """Reference engine.py set_bulk_size -> MXEngineSetBulkSize."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextmanager
def bulk(size):
    """Reference engine.py bulk(size) context manager."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
