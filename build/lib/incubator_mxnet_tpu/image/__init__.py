"""Image IO/augmentation (reference python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from .image_iter import ImageRecordIter  # noqa: F401
from .detection import (CreateDetAugmenter, DetBorrowAug,  # noqa: F401
                        DetHorizontalFlipAug, ImageDetIter)
