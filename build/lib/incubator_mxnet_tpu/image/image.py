"""Image IO + augmenters.

Reference: python/mxnet/image/image.py (2,475 LoC with detection variant):
imdecode/imresize/fixed_crop/random_crop/center_crop/color_normalize/
HorizontalFlipAug/..., `ImageIter`; C++ twin src/io/image_aug_default.cc (565).

TPU-native: decode/augment run on host (PIL instead of OpenCV — no cv2 in
this image); normalization/flip also exist as device ops (ops/image_ops via
nd) so they can fuse into the compiled step.
"""
from __future__ import annotations

import io as _io
import os
import random as _pyrandom

import numpy as _np

from .. import nd
from ..base import MXNetError

__all__ = ["imdecode", "imdecode_np", "imencode", "imread", "imresize",
           "fixed_crop", "random_crop", "center_crop", "resize_short",
           "color_normalize", "random_size_crop", "Augmenter", "ResizeAug",
           "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "LightingAug", "ColorJitterAug", "RandomGrayAug", "HueJitterAug",
           "RandomOrderAug", "CreateAugmenter", "ImageIter", "scale_down"]


def _pil():
    from PIL import Image
    return Image


def imdecode_np(buf, to_rgb=1) -> _np.ndarray:
    """Decode compressed image bytes -> HWC uint8 numpy."""
    img = _pil().open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB") if to_rgb else img.convert("RGB")
    return _np.asarray(img)


def imdecode(buf, to_rgb=1, flag=1, **kwargs):
    """Reference image.py imdecode -> NDArray HWC uint8."""
    return nd.array(imdecode_np(buf, to_rgb), dtype="uint8")


def imencode(img, quality=95, fmt=".jpg"):
    if isinstance(img, nd.NDArray):
        img = img.asnumpy()
    pil_img = _pil().fromarray(_np.asarray(img, _np.uint8))
    out = _io.BytesIO()
    pil_img.save(out, format="JPEG" if fmt in (".jpg", ".jpeg") else "PNG",
                 quality=quality)
    return out.getvalue()


def imread(filename, to_rgb=1, flag=1):
    with open(filename, "rb") as f:
        return imdecode(f.read(), to_rgb)


def imresize(src, w, h, interp=1):
    """Reference image.py imresize."""
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else _np.asarray(src)
    img = _pil().fromarray(_np.asarray(arr, _np.uint8))
    img = img.resize((w, h), _pil().BILINEAR if interp else _pil().NEAREST)
    return nd.array(_np.asarray(img), dtype="uint8")


def scale_down(src_size, size):
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * new_ratio)))
        new_h = int(round(_np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


class Augmenter:
    """Reference image.py Augmenter."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return nd.flip(src, axis=1)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = nd.array(mean) if mean is not None else None
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (src.asnumpy() * self.coef).sum() * (3.0 / src.size)
        return src * alpha + (1.0 - alpha) * gray


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (src.asnumpy() * self.coef).sum(axis=2, keepdims=True)
        return src * alpha + nd.array(gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], _np.float32)
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], _np.float32)

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]], _np.float32)
        t = _np.dot(_np.dot(self.ityiq, bt), self.tyiq).T
        return nd.array(_np.dot(src.asnumpy(), t))


class LightingAug(Augmenter):
    """PCA lighting noise (reference image.py LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,)).astype(_np.float32)
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return src + nd.array(rgb)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness, contrast, saturation):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = []
        if brightness > 0:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation > 0:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self.augs)
        _pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = nd.array([[0.21, 0.21, 0.21],
                             [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]])

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return nd.dot(src, self.mat)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augment pipeline (reference image.py CreateAugmenter;
    C++ twin image_aug_default.cc DefaultImageAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = nd.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = nd.array(mean)
    if std is True:
        std = nd.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = nd.array(std)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python image iterator over .rec or image list
    (reference image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 part_index=0, num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 **kwargs):
        from .. import recordio as rio
        from ..io.io import DataBatch, DataDesc
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._DataBatch = DataBatch
        self.provide_data = [DataDesc(data_name, (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(label_name, (batch_size, label_width)
                                       if label_width > 1 else (batch_size,))]
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_resize", "rand_mirror",
                                                    "mean", "std")})
        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self.imgrec = rio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = rio.MXRecordIO(path_imgrec, "r")
        elif path_imglist or imglist is not None:
            if imglist is None:
                imglist = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        imglist.append((float(parts[1]), parts[-1]))
            self.imglist = [(l if not isinstance(l, (list, tuple)) or
                             len(_np.atleast_1d(l)) > 1 else float(_np.atleast_1d(l)[0]), p)
                            for l, p in imglist]
            self.path_root = path_root
            self.seq = list(range(len(self.imglist)))
        else:
            raise MXNetError("need path_imgrec or path_imglist/imglist")
        if num_parts > 1 and self.seq is not None:
            self.seq = self.seq[part_index::num_parts]
        self.shuffle = shuffle
        self.cur = 0
        self.reset()

    def __iter__(self):
        return self

    def reset(self):
        if self.shuffle and self.seq is not None:
            _np.random.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        from . import image as _self
        from .. import recordio as rio
        if self.seq is not None and self.cur >= len(self.seq):
            raise StopIteration
        if self.imgrec is not None:
            if self.seq is not None:
                rec = self.imgrec.read_idx(self.seq[self.cur])
            else:
                rec = self.imgrec.read()
                if rec is None:
                    raise StopIteration
            self.cur += 1
            header, img = rio.unpack(rec)
            return header.label, img
        label, fname = self.imglist[self.seq[self.cur]]
        self.cur += 1
        with open(os.path.join(self.path_root, fname), "rb") as f:
            return label, f.read()

    def next(self):
        batch_data = _np.zeros((self.batch_size,) + self.data_shape, _np.float32)
        batch_label = _np.zeros((self.batch_size, self.label_width), _np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, buf = self.next_sample()
                img = imdecode(buf)
                for aug in self.auglist:
                    img = aug(img)
                arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
                batch_data[i] = _np.transpose(arr, (2, 0, 1))
                batch_label[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = self.batch_size - i
        return self._DataBatch(data=[nd.array(batch_data)],
                               label=[nd.array(batch_label.squeeze(-1)
                                               if self.label_width == 1 else
                                               batch_label)],
                               pad=pad)

    def __next__(self):
        return self.next()
