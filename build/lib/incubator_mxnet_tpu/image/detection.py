"""Detection image iterator.

Reference: python/mxnet/image/detection.py (ImageDetIter + det augmenters)
and src/io/iter_image_det_recordio.cc. Label wire format per image is the
reference's: a flat float vector [A, B, <A-2 extras>, obj0 .. objN-1] where
A = header width (>= 2), B = per-object width (>= 5: class, x1, y1, x2, y2
in normalized [0,1] coords). Batches pad the object dimension with
`label_pad_value` (-1) so shapes stay static — exactly what MultiBoxTarget
expects downstream.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .image import ImageIter, imdecode
from .. import ndarray as nd


class DetHorizontalFlipAug:
    """Mirror image + boxes with probability p (reference
    DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, img, label):
        if _np.random.uniform() < self.p:
            arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
            img = nd.array(arr[:, ::-1, :].copy())
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return img, label


class DetBorrowAug:
    """Adapt a plain image augmenter to the det interface (reference
    DetBorrowAug). ONLY valid for geometry-preserving augs (cast,
    normalize, color jitter) — a crop/resize-with-crop borrowed this way
    would leave boxes pointing at the wrong region."""

    def __init__(self, aug):
        self.aug = aug

    def __call__(self, img, label):
        return self.aug(img), label


class DetForceResizeAug:
    """Resize the image EXACTLY to (w, h), no cropping. Boxes are in
    normalized [0,1] coordinates, so a pure resize leaves them unchanged
    (reference ForceResizeAug wrapped by CreateDetAugmenter)."""

    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, img, label):
        arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
        if arr.shape[1] != self.size[0] or arr.shape[0] != self.size[1]:
            from .image import imresize
            img = imresize(nd.array(arr), self.size[0], self.size[1],
                           self.interp)
        return img, label


def CreateDetAugmenter(data_shape, resize=0, rand_mirror=False, mean=None,
                       std=None, brightness=0, contrast=0, saturation=0,
                       **kwargs):
    """Det augmenter list (reference CreateDetAugmenter). Geometry is
    handled ONLY by box-aware augs (exact resize, label-aware flip); the
    plain-image crop family is deliberately excluded. Color/cast augs run
    AFTER resize so the resize sees uint8 pixels. Users can append custom
    (img, label) -> (img, label) callables (e.g. IoU-constrained crops)."""
    from .image import CastAug, ColorJitterAug, ColorNormalizeAug, ResizeAug
    augs = []
    if resize > 0:
        # shorter-edge resize scales both dims by the same factor, so
        # normalized boxes are unaffected — safe to borrow
        augs.append(DetBorrowAug(ResizeAug(resize)))
    augs.append(DetForceResizeAug((data_shape[2], data_shape[1])))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    augs.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        augs.append(DetBorrowAug(ColorJitterAug(brightness, contrast,
                                                saturation)))
    if mean is True:
        mean = nd.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = nd.array(mean)
    if std is True:
        std = nd.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = nd.array(std)
    if mean is not None or std is not None:
        augs.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return augs


class ImageDetIter(ImageIter):
    """Detection batches: data (B, C, H, W), label (B, max_objs, obj_width)
    padded with label_pad_value (reference ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, label_pad_width=None,
                 label_pad_value=-1.0, data_name="data",
                 label_name="label", **kwargs):
        _aug_keys = ("resize", "rand_mirror", "mean", "std", "brightness",
                     "contrast", "saturation")
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items() if k in _aug_keys})
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=[], imglist=imglist, data_name=data_name,
                         label_name=label_name, **{
                             k: v for k, v in kwargs.items()
                             if k not in _aug_keys})
        self.det_auglist = aug_list
        self.label_pad_value = float(label_pad_value)
        # scan the dataset once to size the padded label tensor (reference
        # ImageDetIter._estimate_label_shape). When labels are in memory
        # (imglist), read them directly — next_sample() would read every
        # image file just to discard the bytes.
        if label_pad_width is None:
            max_objs, obj_w = 1, 5
            if self.imglist is not None:
                labels = (self.imglist[i][0] for i in self.seq)
            else:
                labels = (lab for lab, _ in self._iter_labels())
            for lab in labels:
                objs = self._parse_det_label(lab)
                max_objs = max(max_objs, objs.shape[0])
                obj_w = max(obj_w, objs.shape[1])
            self.reset()
            label_pad_width = max_objs
            self._obj_width = obj_w
        else:
            self._obj_width = int(kwargs.get("obj_width", 5))
        self.label_shape = (label_pad_width, self._obj_width)
        from ..io.io import DataDesc
        self.provide_label = [DataDesc(label_name,
                                       (batch_size,) + self.label_shape)]

    def _iter_labels(self):
        while True:
            try:
                yield self.next_sample()
            except StopIteration:
                return

    @staticmethod
    def _parse_det_label(label):
        lab = _np.asarray(label, _np.float32).reshape(-1)
        if lab.size < 2:
            raise MXNetError("det label needs [header_width, obj_width, ...]")
        A = int(lab[0])
        B = int(lab[1])
        if A < 2 or B < 5:
            raise MXNetError(f"bad det label header A={A} B={B}")
        body = lab[A:]
        n = body.size // B
        return body[:n * B].reshape(n, B)

    def next(self):
        from ..io.io import DataBatch
        B = self.batch_size
        C, H, W = self.data_shape if len(self.data_shape) == 3 \
            else (1,) + tuple(self.data_shape)
        batch_data = _np.zeros((B, C, H, W), _np.float32)
        batch_label = _np.full((B,) + self.label_shape,
                               self.label_pad_value, _np.float32)
        i = 0
        try:
            while i < B:
                label, buf = self.next_sample()
                img = imdecode(buf)
                objs = self._parse_det_label(label)
                for aug in self.det_auglist:
                    img, objs = aug(img, objs)
                arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
                if arr.shape[:2] != (H, W):
                    # DetForceResizeAug runs first in the default pipeline;
                    # landing here means a custom aug_list dropped it
                    raise MXNetError(
                        f"det image is {arr.shape[:2]} but data_shape wants "
                        f"{(H, W)}; include DetForceResizeAug (it must run "
                        "before cast/normalize augs)")
                batch_data[i] = _np.transpose(arr, (2, 0, 1))
                n = min(objs.shape[0], self.label_shape[0])
                w = min(objs.shape[1], self.label_shape[1])
                batch_label[i, :n, :w] = objs[:n, :w]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return DataBatch(data=[nd.array(batch_data)],
                         label=[nd.array(batch_label)], pad=B - i)
