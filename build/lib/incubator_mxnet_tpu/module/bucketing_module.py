"""BucketingModule: per-bucket executors sharing parameters.

Reference: python/mxnet/module/bucketing_module.py:36 — `sym_gen(bucket_key)`
returns (symbol, data_names, label_names); one Module per seen bucket, all
sharing the default bucket's parameter arrays (`_curr_module` switch
:94-124). On TPU each bucket is one compiled XLA program (static shapes),
which is exactly the reference's per-bucket executor discipline
(docs/faq/bucketing.md).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("BucketingModule requires default_bucket_key")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._init_args = None
        self._monitor = None

    @property
    def symbol(self):
        return self._curr_module._symbol if self._curr_module else None

    @property
    def data_names(self):
        return self._curr_module.data_names

    @property
    def output_names(self):
        return self._curr_module.output_names

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.switch_bucket(self._default_bucket_key, data_shapes,
                               label_shapes)
            return
        # rebind invalidates every bucket executor: stale modules alias the
        # OLD default executor's arrays (reference _reset_bind). Trained
        # values survive the rebind (reference round-trips get/set_params).
        saved_params = self.get_params() if self.params_initialized else None
        self._buckets = {}
        self.params_initialized = False
        self.optimizer_initialized = False
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 force_rebind=False, grad_req=grad_req)
        self._buckets[self._default_bucket_key] = mod
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self.for_training = for_training
        if saved_params is not None:
            arg, aux = saved_params
            mod.init_params(arg_params=arg, aux_params=aux, force_init=True)
            self.params_initialized = True
        self._bind_args = dict(for_training=for_training,
                               inputs_need_grad=inputs_need_grad,
                               grad_req=grad_req)

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Reference bucketing_module.py:94-124: lazily create the bucket's
        module, sharing parameters with the default bucket."""
        if not self.binded:
            raise MXNetError("switch_bucket requires bind()")
        if bucket_key not in self._buckets:
            default = self._buckets[self._default_bucket_key]
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes, **self._bind_args,
                     shared_module=default)
            # share optimizer machinery AND the kvstore so non-default
            # buckets aggregate gradients identically (reference
            # bucketing_module.py borrow_optimizer)
            if default.optimizer_initialized:
                mod._optimizer = default._optimizer
                mod._updater = default._updater
                mod._kvstore = default._kvstore
                mod.optimizer_initialized = True
            if self._monitor is not None:
                mod.install_monitor(self._monitor)
            self._buckets[bucket_key] = mod
        # parameter arrays are aliased across buckets (Module.bind
        # shared_module), so switching needs no copying
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        default = self._buckets[self._default_bucket_key]
        default.init_optimizer(kvstore, optimizer, optimizer_params,
                               force_init=force_init)
        # all buckets share the one updater + kvstore (optimizer state is
        # keyed by parameter name, so bucket argument order is irrelevant)
        for mod in self._buckets.values():
            mod._optimizer = default._optimizer
            mod._updater = default._updater
            mod._kvstore = default._kvstore
            mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._curr_bucket_key
        self.switch_bucket(key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._curr_module.save_checkpoint(prefix, epoch, save_optimizer_states)
