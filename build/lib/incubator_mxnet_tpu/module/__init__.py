"""`mx.mod`: Module training API (reference python/mxnet/module/, 4,007 LoC)."""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule

__all__ = ["BaseModule", "Module", "BucketingModule"]
