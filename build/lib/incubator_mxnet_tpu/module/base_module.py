"""BaseModule: the fit/score/predict epoch loop.

Reference: python/mxnet/module/base_module.py — `fit:409` (epoch loop:
forward_backward -> update -> update_metric -> batch callbacks -> epoch
checkpoint + validation), `score:178`, `predict:320`. The loop here is the
same shape; the compute inside each step is one XLA program per executor.
"""
from __future__ import annotations

import logging
import time

from .. import metric as _metric
from ..base import MXNetError
from ..model import BatchEndParam

__all__ = ["BaseModule"]


def _as_metric(m):
    if isinstance(m, _metric.EvalMetric):
        return m
    return _metric.create(m)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract interface (implemented by Module/BucketingModule) ---------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # -- composite loops ----------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=None,
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """Reference base_module.py:409."""
        if num_epoch is None:
            raise MXNetError("fit requires num_epoch")
        optimizer_params = optimizer_params or {"learning_rate": 0.01}

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        eval_metric = _as_metric(eval_metric)
        validation_metric = (_as_metric(validation_metric)
                             if validation_metric is not None else eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            arg, aux = self.get_params()
            self.set_params(arg, aux, allow_missing=False, force_init=True,
                            allow_extra=True)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg, aux)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, epoch=0,
              sparse_row_id_fn=None, reset=True):
        """Reference base_module.py:178."""
        if not self.binded or not self.params_initialized:
            raise MXNetError("score() requires bind + init_params")
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        nbatch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback is not None:
            param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                  eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """Reference base_module.py:320."""
        from .. import nd

        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[0:o.shape[0] - pad] for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concatenate([o[i] for o in output_list], axis=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def iter_predict(self, eval_data, num_batch=None, reset=True,
                     sparse_row_id_fn=None):
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[0:o.shape[0] - pad] for o in self.get_outputs()]
            yield outs, nbatch, eval_batch

    # -- misc ----------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def install_monitor(self, mon):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        from .. import nd
        arg_params, aux_params = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        from .. import nd
        save_dict = nd.load(fname)
        arg_params, aux_params = {}, {}
        for k, v in save_dict.items():
            tp, name = k.split(":", 1)
            (arg_params if tp == "arg" else aux_params)[name] = v
        self.set_params(arg_params, aux_params)


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return x
    return [x]
