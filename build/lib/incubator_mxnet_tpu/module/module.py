"""Module: a Symbol bound to data shapes with optimizer state.

Reference: python/mxnet/module/module.py — `bind:364` (builds
DataParallelExecutorGroup over per-device simple_bind), `init_optimizer:474`
(kvstore decision via model._create_kvstore), `forward:575`, `backward:629`,
`update:646` (kv push/pull + Updater).

TPU-native redesign: one Executor over the whole (possibly sharded) program —
batch slicing across devices is XLA sharding, not a Python executor group.
The kvstore path is kept for API parity: updates route through
kvstore.push/pull when a kvstore is given (our kvstore rides mesh
collectives), and through a local Updater otherwise.
"""
from __future__ import annotations

import logging

from .. import initializer as _init
from .. import optimizer as _opt
from ..base import MXNetError
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._context = context
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._update_on_kvstore = False
        self._preload_opt_states = None
        self._preload_params = None

    # -- bind ---------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def output_shapes(self):
        return [(n, o.shape) for n, o in
                zip(self.output_names, self._exec.outputs)] \
            if self._exec and self._exec.outputs else None

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Reference module.py:364."""
        if self.binded and not force_rebind:
            return
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes or [])
        self.for_training = for_training
        self._inputs_need_grad = inputs_need_grad

        shapes = {}
        dtypes = {}
        for desc in self._data_shapes + self._label_shapes:
            name, shape = desc[0], desc[1]
            shapes[name] = tuple(shape)
            if len(desc) > 2 and desc[2] is not None:
                dtypes[name] = desc[2]

        grad_reqs = {}
        for n in self._symbol.list_arguments():
            if n in self._param_names and n not in self._fixed_param_names \
                    and for_training:
                grad_reqs[n] = grad_req if isinstance(grad_req, str) else \
                    grad_req.get(n, "write")
            elif n in self._data_names and inputs_need_grad and for_training:
                grad_reqs[n] = "write"
            else:
                grad_reqs[n] = "null"

        from ..executor import Executor
        old_exec = self._exec if shared_module is None else shared_module._exec
        self._exec = Executor.simple_bind(self._symbol, self._context,
                                          grad_req=grad_reqs,
                                          type_dict=dtypes, **shapes)
        if shared_module is not None and shared_module._exec is not None:
            # ALIAS parameter NDArrays with the shared module (reference:
            # bucket executors share arg arrays via shared_exec memory pool,
            # executor_group.py) — updates through either executor are
            # visible to both
            src = shared_module._exec
            for n in list(self._exec.arg_dict):
                if n in src.arg_dict and \
                        src.arg_dict[n].shape == self._exec.arg_dict[n].shape:
                    self._exec.arg_dict[n] = src.arg_dict[n]
            for n in list(self._exec.aux_dict):
                if n in src.aux_dict and \
                        src.aux_dict[n].shape == self._exec.aux_dict[n].shape:
                    self._exec.aux_dict[n] = src.aux_dict[n]
            self.params_initialized = shared_module.params_initialized
        elif old_exec is not None:
            # re-bind keeps parameter values
            self._exec.copy_params_from(
                {n: a for n, a in old_exec.arg_dict.items()
                 if n in self._param_names},
                old_exec.aux_dict, allow_extra_params=True)
        self.binded = True
        if self._preload_params is not None:
            # checkpoint loaded via Module.load binds into initialized params
            arg, aux = self._preload_params
            self.init_params(arg_params=arg, aux_params=aux, force_init=True)
            self._preload_params = None

    # -- params -------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("init_params requires bind()")
        if initializer is None:
            initializer = _init.Uniform(0.01)
        elif isinstance(initializer, str):
            initializer = _init.create(initializer)

        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr[:] = arg_params[name]
            elif arg_params is not None and not allow_missing:
                raise MXNetError(f"init_params: missing arg {name}")
            else:
                initializer(_init.InitDesc(name), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr[:] = aux_params[name]
            elif aux_params is not None and not allow_missing:
                raise MXNetError(f"init_params: missing aux {name}")
            else:
                initializer(_init.InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        if not self.binded:
            raise MXNetError("get_params requires bind()")
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    # -- optimizer ----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        """Reference module.py:474 + model._create_kvstore."""
        if self.optimizer_initialized and not force_init:
            return
        optimizer_params = dict(optimizer_params or {"learning_rate": 0.01})
        if isinstance(optimizer, str):
            # reference module.py:498: default rescale_grad = 1/batch_size
            # (SoftmaxOutput's default normalization sums over the batch)
            if "rescale_grad" not in optimizer_params and self.binded:
                batch = self._data_shapes[0][1][0]
                optimizer_params["rescale_grad"] = 1.0 / batch
            optimizer = _opt.create(optimizer, **optimizer_params)
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)

        kv = None
        if kvstore is not None and not isinstance(kvstore, str):
            kv = kvstore
        elif isinstance(kvstore, str) and kvstore not in ("local", None):
            from .. import kvstore as _kvs
            kv = _kvs.create(kvstore)
        self._kvstore = kv
        if kv is not None:
            for name in self._param_names:
                kv.init(name, self._exec.arg_dict[name])
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- step ---------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Reference module.py:646 -> model._update_params[_on_kvstore]."""
        if not self.optimizer_initialized:
            raise MXNetError("update() requires init_optimizer()")
        # keys are parameter NAMES so optimizer state and kvstore entries
        # stay consistent across bucket executors whose argument orders may
        # differ (reference keys kvstore by name, kvstore.py:123)
        if self._kvstore is not None:
            from ..ndarray import NDArray
            for name in self._param_names:
                g = self._exec.grad_dict.get(name)
                if g is None:
                    continue
                self._kvstore.push(name, g)
                # pull rebinds the buffer wholesale, so a zero-copy view is
                # enough as the out slot (no per-step weight copy)
                agg = NDArray(g._data)
                self._kvstore.pull(name, out=agg)
                self._updater(name, agg, self._exec.arg_dict[name])
        else:
            for name in self._param_names:
                g = self._exec.grad_dict.get(name)
                if g is None:
                    continue
                self._updater(name, g, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        if not self._inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    # -- persistence --------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        from ..model import save_checkpoint
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        symbol, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preload_params = (arg, aux)
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_optimizer_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer=True))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def install_monitor(self, mon):
        mon.install(self._exec)

    def reshape(self, data_shapes, label_shapes=None):
        shapes = {d[0]: tuple(d[1]) for d in data_shapes}
        if label_shapes:
            shapes.update({d[0]: tuple(d[1]) for d in label_shapes})
        self._exec = self._exec.reshape(**shapes)
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes or [])
