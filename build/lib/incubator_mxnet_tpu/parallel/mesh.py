"""Device mesh construction.

Reference analog: the device lists threaded through Module/executor_group
(python/mxnet/module/executor_group.py decide_slices) and kvstore device
groups. TPU-native: one jax.sharding.Mesh names every parallelism axis; axes
order puts the fastest-varying (tp) innermost so tensor-parallel collectives
ride the shortest ICI hops.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["make_mesh", "local_mesh_axis_sizes"]


def make_mesh(axis_shapes=None, devices=None, axis_names=None):
    """Build a Mesh.

    axis_shapes: dict like {"dp": 2, "tp": 4} (order = major->minor), or None
    for all devices on a single "dp" axis. -1 means "remaining devices".
    """
    import numpy as _np
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_shapes is None:
        axis_shapes = {"dp": n}
    names = list(axis_shapes.keys())
    sizes = list(axis_shapes.values())
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {n}")
    arr = _np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def local_mesh_axis_sizes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))
