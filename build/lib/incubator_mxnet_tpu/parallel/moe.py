"""Mixture-of-Experts with expert parallelism (ep).

ABSENT in the reference (SURVEY §2.3 lists EP as a first-class TPU goal
beyond parity). Design: expert WEIGHTS are sharded over an `ep` mesh
axis; gating/dispatch run replicated (tokens are replicated across ep —
token sharding composes via a separate dp axis), each shard computes its
expert slice, and outputs are all-gathered for the combine. This shards
the dominant cost (expert FFN weights + matmuls) across the axis; the
GShard-style all_to_all token exchange, which additionally shards the
dispatch/combine tensors, is the token-sharded extension and is not
implemented here.

Capacity discipline keeps shapes static for XLA: each expert processes at
most `capacity` tokens; overflow tokens are dropped (their combine weight
is 0), matching Switch-Transformer semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ._compat import shard_map

__all__ = ["moe_gate", "moe_apply", "moe_apply_a2a", "moe_sharded",
           "init_moe_params"]


def moe_gate(x, wg, k=1, capacity_factor=1.25):
    """Top-k gating (Switch for k=1). x: (N, d); wg: (d, E).
    Returns (dispatch (N, E, C) one-hot, combine (N, E, C) weights,
    aux_loss) with C = capacity."""
    N, _ = x.shape
    E = wg.shape[1]
    logits = (x.astype(jnp.float32) @ wg.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)            # (N, E)
    C = int(max(1, capacity_factor * k * N / E))

    dispatch = jnp.zeros((N, E, C), jnp.bool_)
    combine = jnp.zeros((N, E, C), jnp.float32)
    remaining = probs
    # queue positions are CUMULATIVE across the k rounds — restarting the
    # count per round would assign two tokens the same (expert, slot) and
    # sum their inputs in the expert queue
    counts = jnp.zeros((E,), jnp.int32)
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)        # (N,)
        gate = jnp.take_along_axis(remaining, choice[:, None], 1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)
        # position of each token within its expert's queue, offset by the
        # slots already consumed in earlier rounds
        pos = counts[None, :] + jnp.cumsum(onehot, axis=0) - onehot  # (N,E)
        in_cap = (pos < C) & onehot.astype(bool)
        pos_c = jnp.clip(pos, 0, C - 1)
        slot = jax.nn.one_hot(pos_c, C, dtype=jnp.bool_) & \
            in_cap[..., None]                           # (N, E, C)
        dispatch = dispatch | slot
        combine = combine + slot.astype(jnp.float32) * gate[:, None, None]
        remaining = remaining * (1.0 - onehot)
        counts = counts + jnp.sum(onehot, axis=0)
    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e
    f = jnp.mean((probs == jnp.max(probs, -1, keepdims=True)).astype(
        jnp.float32), axis=0)
    P = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * P)
    return dispatch, combine, aux


def moe_apply(x, params, axis_name=None, k=1, capacity_factor=1.25,
              activation=jax.nn.gelu):
    """One MoE FFN layer. x: (N, d). params: dict with
    wg (d, E), w1 (E_local, d, dff), w2 (E_local, dff, d).

    With axis_name (inside shard_map): E = E_local * ep_size; each shard
    builds only ITS experts' input queues (gating is replicated, the
    dispatch tensor is sliced to the local expert block before the queue
    einsum), runs its expert FFNs, and all-gathers the expert outputs for
    the replicated combine. Without axis_name: E = E_local (dense
    single-shard MoE, the numeric oracle)."""
    wg, w1, w2 = params["wg"], params["w1"], params["w2"]
    N, d = x.shape
    ep = 1 if axis_name is None else lax.psum(1, axis_name)
    e_local = w1.shape[0]
    E = e_local * ep

    dispatch, combine, aux = moe_gate(x, wg, k=k,
                                      capacity_factor=capacity_factor)
    C = dispatch.shape[-1]
    if axis_name is not None:
        # slice dispatch to the local expert block FIRST so the queue
        # einsum costs O(N * e_local * C * d) per shard, not O(N * E * C * d)
        r = lax.axis_index(axis_name)
        local_disp = lax.dynamic_slice_in_dim(dispatch, r * e_local,
                                              e_local, axis=1)  # (N, e_l, C)
        local_in = jnp.einsum("nec,nd->ecd", local_disp.astype(x.dtype), x)
        h = activation(jnp.einsum("ecd,edf->ecf", local_in, w1))
        local_out = jnp.einsum("ecf,efd->ecd", h, w2)   # (e_local, C, d)
        out = lax.all_gather(local_out, axis_name, axis=0,
                             tiled=True)                # (E, C, d)
    else:
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)
        h = activation(jnp.einsum("ecd,edf->ecf",
                                  expert_in.reshape(e_local, C, d), w1))
        out = jnp.einsum("ecf,efd->ecd", h, w2).reshape(E, C, d)
    y = jnp.einsum("nec,ecd->nd", combine.astype(out.dtype), out)
    return y, aux


def moe_apply_a2a(x, params, axis_name, k=1, capacity_factor=1.25,
                  activation=jax.nn.gelu):
    """GShard-style token-sharded MoE — the all-to-all dispatch variant.

    Run INSIDE shard_map with BOTH tokens and experts sharded over
    `axis_name` (in a composed mesh this is the `ep` axis, or the `dp`
    axis when experts ride the data-parallel groups, the GShard layout).

    x: (N_local, d) — THIS shard's tokens. params as in moe_apply with
    w1/w2 holding the local e_local = E/ep expert slices.

    Wire pattern (all shapes static):
      1. local top-k gating against the full E-expert router (wg is
         replicated) with per-shard capacity C,
      2. build per-(expert, slot) queues from local tokens:
         (E, C, d) = dispatch^T @ x,
      3. `all_to_all` over the EXPERT dim: each shard keeps its e_local
         experts' queues from every peer -> (ep * C) slots per local
         expert,
      4. run the local expert FFNs,
      5. `all_to_all` back (transpose of 3), combine locally.

    The backward schedule is the transpose: autodiff turns each
    all_to_all into the reverse all_to_all, so expert-weight grads stay
    shard-local and token grads return to their home shard — no psum over
    `axis_name` is needed for expert weights (and none must be applied:
    they are sharded, not replicated, over this axis).

    Returns (y (N_local, d), aux_loss). Numerics match moe_apply run
    independently on each shard's tokens with the full expert set.
    """
    wg, w1, w2 = params["wg"], params["w1"], params["w2"]
    N, d = x.shape
    ep = lax.psum(1, axis_name)
    e_local = w1.shape[0]
    E = e_local * ep

    dispatch, combine, aux = moe_gate(x, wg, k=k,
                                      capacity_factor=capacity_factor)
    C = dispatch.shape[-1]
    # 2. per-expert queues of MY tokens: (E, C, d)
    queues = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)
    # 3. exchange: split the E dim across shards, concat peers' blocks.
    # After this, shard r holds (ep, e_local, C, d): peer p's queue for
    # my experts [r*e_local, (r+1)*e_local).
    queues = queues.reshape(ep, e_local, C, d)
    queues = lax.all_to_all(queues, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    # 4. local expert FFN over every peer's slots at once
    h = activation(jnp.einsum("pecd,edf->pecf", queues, w1))
    out = jnp.einsum("pecf,efd->pecd", h, w2)          # (ep, e_local, C, d)
    # 5. route results back to the token-home shards (transpose of 3)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    out = out.reshape(E, C, d)
    y = jnp.einsum("nec,ecd->nd", combine.astype(out.dtype), out)
    return y, aux


def init_moe_params(key, d, dff, n_experts, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / jnp.sqrt(d)
    return {
        "wg": (jax.random.normal(k1, (d, n_experts)) * scale).astype(dtype),
        "w1": (jax.random.normal(k2, (n_experts, d, dff)) * scale
               ).astype(dtype),
        "w2": (jax.random.normal(k3, (n_experts, dff, d)) *
               (1.0 / jnp.sqrt(dff))).astype(dtype),
    }


def moe_sharded(x, params, mesh, axis="ep", k=1, capacity_factor=1.25):
    """Whole-layer entry: w1/w2 sharded over `axis` on their expert dim,
    wg and x replicated. One compiled program; the only collective is the
    expert-output all_gather before the combine (see module docstring)."""
    from jax.sharding import PartitionSpec as P

    spec_p = {"wg": P(), "w1": P(axis), "w2": P(axis)}

    def inner(params, xx):
        return moe_apply(xx, params, axis_name=axis, k=k,
                         capacity_factor=capacity_factor)

    return shard_map(inner, mesh, in_specs=(spec_p, P()),
                     out_specs=(P(), P()))(params, x)
