"""JAX API compatibility shims for the parallel stack.

jax moved shard_map from `jax.experimental.shard_map` (kwarg `check_rep`) to
`jax.shard_map` (keyword-only, kwarg `check_vma`). We feature-detect once at
import so every caller in this package works on either API, with replication
checking disabled (our loss reductions pmean over every mesh axis themselves).
"""
from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map"]


def _make_shard_map():
    new = getattr(jax, "shard_map", None)
    if new is not None:
        sig = inspect.signature(new)
        if "check_vma" in sig.parameters:
            def shard_map(f, mesh, in_specs, out_specs):
                return new(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            return shard_map
    from jax.experimental.shard_map import shard_map as old

    sig = inspect.signature(old)
    kw = {}
    if "check_rep" in sig.parameters:
        kw["check_rep"] = False
    elif "check_vma" in sig.parameters:
        kw["check_vma"] = False

    def shard_map(f, mesh, in_specs, out_specs):
        return old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

    return shard_map


shard_map = _make_shard_map()
