"""Pipeline parallelism (pp): GPipe-style microbatch schedule over a mesh
axis.

The reference's only model parallelism is layer placement via `group2ctx`
(src/executor/graph_executor.cc:986 device-placement pass + cross-device
copies) with NO pipelining — devices idle while one executes its layers.
TPU-native redesign: stages live on a `pp` mesh axis inside shard_map;
microbatches flow stage-to-stage with `lax.ppermute` on a `lax.scan`
steady-state loop, so after the fill phase every stage computes every
step (classic GPipe bubble of (S-1)/(S-1+M)).

All-XLA: no host scheduling, the whole pipeline is one compiled program
that composes with dp/tp/sp axes of the same mesh.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ._compat import shard_map

__all__ = ["pipeline_apply", "pipeline_train_apply", "pipeline_sharded"]


def pipeline_apply(stage_fn, stage_params, x, axis_name, n_microbatches):
    """Run INSIDE shard_map. Executes `stage_fn(stage_params, h)` on each
    of the S pipeline stages (S = size of `axis_name`), feeding the output
    of stage s to stage s+1, microbatch by microbatch.

    stage_params: this device's stage parameters (already sharded on the
    pp axis). x: the FULL batch (replicated across pp), split into
    `n_microbatches` along axis 0. Returns the full batch of final-stage
    outputs (replicated across pp ranks via a psum broadcast).

    Constraint: every stage must map a (mb, ...) activation to the SAME
    shape and dtype — the ring buffer that carries activations between
    stages (and the collected outputs) has one static shape. Put any
    projection to a different width inside a stage, not between stages.
    """
    outs, _ = pipeline_train_apply(
        lambda p, h: (stage_fn(p, h), jnp.float32(0)),
        stage_params, x, axis_name, n_microbatches)
    return outs


def pipeline_train_apply(stage_fn, stage_params, x, axis_name,
                         n_microbatches):
    """pipeline_apply for TRAINING stages: stage_fn(params, h) returns
    (h_out, aux) where aux is a scalar auxiliary loss (e.g. MoE load
    balancing). Differentiating through this function yields the pipeline
    BACKWARD schedule automatically: the transpose of the forward scan
    runs the stages in reverse with the ppermute ring inverted, microbatch
    by microbatch, accumulating each stage's weight gradient across
    microbatches in the scan-carry cotangent — the GPipe backward.

    aux is only meaningful for steps where a stage holds a real microbatch
    (during fill/drain, stages chew zeros); those contributions are masked
    out. Returns (outputs (B, ...), aux_mean) with aux_mean the mean over
    the S * M real (stage, microbatch) visits.
    """
    S = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches}")
    mb = B // n_microbatches
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])

    total = n_microbatches + S - 1     # fill + steady + drain
    out0 = jnp.zeros_like(micro)
    carry0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    aval = jax.eval_shape(stage_fn, stage_params, carry0)[0]
    if aval.shape != carry0.shape or aval.dtype != carry0.dtype:
        raise ValueError(
            f"pipeline stage must preserve activation shape/dtype: got "
            f"{aval.shape}/{aval.dtype} from {carry0.shape}/{carry0.dtype}; "
            "move width changes inside a stage")

    def step(carry, t):
        h_prev, outs, aux_acc = carry
        mb_idx = jnp.clip(t, 0, n_microbatches - 1)
        inject = lax.dynamic_index_in_dim(micro, mb_idx, 0, keepdims=False)
        h_in = jnp.where(rank == 0, inject, h_prev)
        h_out, aux = stage_fn(stage_params, h_in)
        # my microbatch at step t is t - rank; mask fill/drain visits
        valid = jnp.logical_and(t - rank >= 0, t - rank < n_microbatches)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        out_idx = jnp.clip(t - (S - 1), 0, n_microbatches - 1)
        take = jnp.logical_and(rank == S - 1, t >= S - 1)
        outs = lax.cond(
            take,
            lambda o: lax.dynamic_update_index_in_dim(
                o, h_out.astype(o.dtype), out_idx, 0),
            lambda o: o, outs)
        h_next = lax.ppermute(
            h_out, axis_name, [(i, (i + 1) % S) for i in range(S)])
        return (h_next, outs, aux_acc), None

    (_, outs, aux_acc), _ = lax.scan(
        step, (carry0, out0, jnp.float32(0)), jnp.arange(total))
    outs = lax.psum(jnp.where(rank == S - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    aux_mean = lax.psum(aux_acc, axis_name) / (S * n_microbatches)
    return outs.reshape((B,) + outs.shape[2:]), aux_mean


def pipeline_sharded(stage_fn, params_stacked, x, mesh, axis="pp",
                     n_microbatches=None):
    """Whole-pipeline entry: params_stacked has leading axis S (one slice
    per stage) and is sharded over `axis`; x is replicated. Compiles ONE
    program containing the full schedule."""
    from jax.sharding import PartitionSpec as P

    S = mesh.shape[axis]
    if n_microbatches is None:
        n_microbatches = S
    leaves = jax.tree_util.tree_leaves(params_stacked)
    for leaf in leaves:
        if leaf.shape[0] != S:
            raise ValueError(
                f"stacked params lead dim {leaf.shape[0]} != pipeline "
                f"stages {S} (axis {axis!r}); group layers per stage "
                "inside stage_fn instead")
    spec_p = jax.tree_util.tree_map(lambda _: P(axis), params_stacked)

    def inner(params, xx):
        local = jax.tree_util.tree_map(lambda a: a[0], params)  # my stage
        return pipeline_apply(stage_fn, local, xx, axis, n_microbatches)

    return shard_map(inner, mesh, in_specs=(spec_p, P()),
                     out_specs=P())(params_stacked, x)
