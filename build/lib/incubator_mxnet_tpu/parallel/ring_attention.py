"""Ring attention: exact attention over sequences sharded across devices.

The reference has NO sequence/context parallelism (SURVEY.md §5.7 — its
longest-sequence story is BucketingModule + fused RNN). This module is the
TPU-native capability that replaces it at pod scale: the sequence axis lives
on a mesh axis ("sp"); K/V blocks rotate around the ring with
`lax.ppermute` while each device accumulates its queries' attention in
flash-style (running max + running sum) form, so peak memory is O(seq/devices)
and the N^2 score matrix never materializes globally.

Written against jax.shard_map; compute per hop is one (q_blk x k_blk^T) MXU
matmul, overlapping the next hop's ppermute (XLA schedules the collective
permute concurrently with the matmul of the current block).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ring_attention_sharded", "attention_reference"]


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """Plain single-device attention, the numeric oracle for the ring version.
    q,k,v: (B, T, H, D)."""
    B, T, H, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / jnp.sqrt(D).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, k.shape[1]), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _block_attn(q, k, v, scale, mask):
    """Scores for one (q_block, k_block) pair + flash accumulators.
    Returns (unnormalized out, row max, row sumexp)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                      # (B,H,Q)
    # guard fully-masked rows
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)                      # (B,H,Q)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)      # (B,Q,H,D)
    return o, m_safe, l, jnp.isfinite(m)


def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None):
    """Runs INSIDE shard_map: q,k,v are the local sequence shards (B,t,H,D);
    axis_name is the sp mesh axis. Exact (non-approximate) attention."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, t, H, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / jnp.sqrt(D).astype(q.dtype)

    o0 = jnp.zeros((B, t, H, D), jnp.float32)
    m0 = jnp.full((B, H, t), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, t), jnp.float32)

    def body(i, carry):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src_idx = (my_idx - i) % axis_size  # whose K/V block we hold this hop
        if causal:
            # q position block my_idx attends k block src_idx if src < mine,
            # diagonal uses a triangular mask
            q_pos = my_idx * t + jnp.arange(t)
            k_pos = src_idx * t + jnp.arange(t)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        else:
            mask = None
        o_b, m_b, l_b, valid = _block_attn(q, k_cur, v_cur, scale, mask)
        o_b = o_b.astype(jnp.float32)
        m_b = m_b.astype(jnp.float32)
        l_b = l_b.astype(jnp.float32)
        # flash-style merge of (o_acc,m_acc,l_acc) with the new block
        has = jnp.any(valid, axis=-1) if valid.ndim == m_b.ndim + 1 else valid
        m_b = jnp.where(has, m_b, -jnp.inf)
        m_new = jnp.maximum(m_acc, m_b)
        m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        c_old = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_new_safe), 0.0)
        c_new = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - m_new_safe), 0.0)
        l_new = l_acc * c_old + l_b * c_new
        o_new = o_acc * jnp.transpose(c_old, (0, 2, 1))[..., None] + \
            o_b * jnp.transpose(c_new, (0, 2, 1))[..., None]
        # rotate K/V to the next device on the ring
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt)

    o, m, l, _, _ = lax.fori_loop(0, axis_size, body, (o0, m0, l0, k, v))
    denom = jnp.where(l > 0, l, 1.0)
    out = o / jnp.transpose(denom, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False,
                           sm_scale=None):
    """shard_map wrapper: q,k,v (B,T,H,D) get sharded on T over `axis_name`
    (and batch over 'dp' if present) and attention runs as a ring."""
    from jax.sharding import PartitionSpec as P
    from ._compat import shard_map

    batch_axis = "dp" if "dp" in mesh.axis_names else None
    spec = P(batch_axis, axis_name, None, None)

    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                          sm_scale=sm_scale),
        mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
