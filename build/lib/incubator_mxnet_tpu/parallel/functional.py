"""Functionalize a Gluon block: (params pytree, pure apply fn).

This is the bridge from the imperative Gluon API to pjit-able SPMD programs —
the role GraphExecutor::Init plays in the reference (src/executor/
graph_executor.cc:388: bind a symbolic graph + arrays into an executable),
re-imagined: the "graph" is a traced jax function, the "arrays" a params
pytree keyed by parameter name.
"""
from __future__ import annotations

from collections import OrderedDict

from .. import autograd
from ..ndarray import random as _rnd
from ..ndarray.ndarray import NDArray

__all__ = ["functionalize"]


def functionalize(net, example_inputs, training=True):
    """Returns (params: OrderedDict[str, jax.Array], apply_fn).

    apply_fn(params, rng, *input_arrays) -> (outputs_pytree, state_updates)
    is pure/traceable; state_updates maps param name -> new value (BatchNorm
    running stats) to be applied between steps (or folded into params by the
    caller's train step).
    """
    from ..gluon.block import _StateWriteScope, _TraceScope, _flatten_outputs

    inputs_nd = [x if isinstance(x, NDArray) else NDArray(x)
                 for x in example_inputs]
    # resolve deferred shapes with one abstract pass
    import jax
    # the state scope swallows traced stat writes (BatchNorm running stats)
    # so abstract tracers never land in Parameters
    with _TraceScope(), autograd.pause(train_mode=training), \
            _rnd._TraceKeyScope(jax.random.PRNGKey(0)), _StateWriteScope():
        jax.eval_shape(
            lambda *xs: _abstract(net, xs),
            *[jax.ShapeDtypeStruct(x._data.shape, x._data.dtype)
              for x in inputs_nd])

    plist = net.collect_params()
    for p in plist.values():
        if p._data is None:
            p._finish_deferred_init()
    param_list = [plist[k] for k in sorted(plist.keys())]
    params = OrderedDict((p.name, p.data()._data) for p in param_list)

    def apply_fn(params_dict, rng, *input_arrays):
        wrapped = [NDArray(a) for a in input_arrays]
        old = []
        for p in param_list:
            old.append(p._data._data)
            p._data._data = params_dict[p.name]
        try:
            with _TraceScope(), _rnd._TraceKeyScope(rng), \
                    autograd.pause(train_mode=training), \
                    _StateWriteScope() as sw:
                out = net._eager_forward(*wrapped) if hasattr(net, "_eager_forward") \
                    else net(*wrapped)
        finally:
            for p, o in zip(param_list, old):
                p._data._data = o
        flat, rebuild = _flatten_outputs(out)
        return tuple(o._data for o in flat), dict(sw.writes)

    return params, apply_fn


def _abstract(net, xs):
    from ..gluon.block import _flatten_outputs
    wrapped = [NDArray(t) for t in xs]
    out = net._eager_forward(*wrapped) if hasattr(net, "_eager_forward") \
        else net(*wrapped)
    flat, _ = _flatten_outputs(out)
    return tuple(o._data for o in flat)
