"""2-bit gradient compression: bit-packed wire format + quantized
collectives.

Reference: src/kvstore/gradient_compression.cc:44-60 +
gradient_compression-inl.h CUDA kernels (2-bit stochastic-sign
quantization with error-feedback residual, packed 16 values per uint32
for the PS wire) and the server's DataHandleCompressed
(kvstore_dist_server.h:602).

TPU-native design: the pack/unpack are vectorized bit ops (XLA fuses
them); the fused quantize+residual+pack hot path is also provided as a
Pallas kernel (TPU Mosaic; interpreter elsewhere) per the accelerator
guide's "fuse what the compiler won't" rule. The collective is
`quantized_psum`: each shard packs its block (16x fewer wire bytes),
`all_gather`s the packed payload over the axis, and dequantize-sums
locally — a QSGD-style all-reduce with one quantization error per
contributor, carried forward by the residual.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ._compat import shard_map

__all__ = ["two_bit_pack", "two_bit_unpack", "quantize_pack",
           "quantize_pack_pallas", "quantized_psum", "quantized_allreduce"]

_GROUP = 16      # 2 bits x 16 values per uint32


def _codes(c, threshold):
    # 0 -> 0, +threshold -> 1, -threshold -> 2 (the reference's 2-bit states)
    return jnp.where(c >= threshold, jnp.uint32(1),
                     jnp.where(c <= -threshold, jnp.uint32(2),
                               jnp.uint32(0)))


def two_bit_pack(c, threshold):
    """Flat float array -> uint32 array of ceil(n/16) packed codes."""
    flat = c.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % _GROUP
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    codes = _codes(flat, threshold).reshape(-1, _GROUP)
    shifts = (jnp.arange(_GROUP, dtype=jnp.uint32) * 2)[None, :]
    return jnp.sum(codes << shifts, axis=1, dtype=jnp.uint32)


def two_bit_unpack(packed, n, threshold, dtype=jnp.float32):
    """Inverse of two_bit_pack: uint32 codes -> flat (n,) float array."""
    shifts = (jnp.arange(_GROUP, dtype=jnp.uint32) * 2)[None, :]
    codes = (packed[:, None] >> shifts) & jnp.uint32(3)
    vals = jnp.where(codes == 1, jnp.asarray(threshold, dtype),
                     jnp.where(codes == 2, jnp.asarray(-threshold, dtype),
                               jnp.asarray(0, dtype)))
    return vals.reshape(-1)[:n]


def quantize(g, residual, threshold):
    """THE 2-bit quantization rule (single source of truth — the kvstore
    push path, the packed wire, and the Pallas kernel all call this):
    c = g + residual; q = sign(c)*threshold where |c| >= threshold else 0;
    returns (q, new_residual = c - q)."""
    c = g + residual
    q = jnp.where(c >= threshold, threshold,
                  jnp.where(c <= -threshold, -threshold, 0.0)
                  ).astype(c.dtype)
    return q, c - q


def quantize_pack(g, residual, threshold):
    """Error-feedback quantize + pack in one step:
    returns (packed uint32, new_residual) with new_residual = c - q."""
    c = g.reshape(-1) + residual.reshape(-1)
    _, new_res = quantize(c, jnp.zeros_like(c), threshold)
    return two_bit_pack(c, threshold), new_res.reshape(g.shape)


# ---------------------------------------------------------------------------
# Pallas fused kernel: quantize + residual + pack one (rows, 2048) tile at
# a time — 2048 floats in, 128 uint32 out per row (VPU lane-width friendly).
# ---------------------------------------------------------------------------

_TILE = 2048


def _qp_kernel(g_ref, r_ref, thr_ref, packed_ref, newr_ref):
    # blocks are (rows, 16, 128): plane k holds code bit-pair k of each of
    # the row's 128 packed words. Packing is a static 16-step loop over
    # full-lane (rows, 128) slices — no reshape, no minor-dim reduction,
    # no unsigned arithmetic, all of which Mosaic refuses to lower.
    g = g_ref[...]
    r = r_ref[...]
    t = thr_ref[0, 0]
    _, newr_ref[...] = quantize(g, r, t)
    c = g + r
    acc = jnp.zeros(c.shape[:1] + c.shape[2:], jnp.int32)
    for k in range(_GROUP):
        ck = c[:, k, :]
        code = jnp.where(ck >= t, 1, jnp.where(ck <= -t, 2, 0))
        acc = acc | (code << (2 * k))
    packed_ref[...] = acc.astype(jnp.uint32)


def quantize_pack_pallas(g, residual, threshold, block_rows=8):
    """Pallas version of quantize_pack (interpret mode off-TPU); the packed
    wire bytes are identical to two_bit_pack's. Internally the flat input is
    padded to (rows, 2048) tiles and pre-transposed (by XLA, outside the
    kernel) to (rows, 16, 128) so that element [i, k, l] is flat
    [i*2048 + l*16 + k] — the kernel then packs lane-wise."""
    from jax.experimental import pallas as pl

    shape = g.shape
    flat = g.reshape(-1)
    res = residual.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % _TILE
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        res = jnp.concatenate([res, jnp.zeros((pad,), res.dtype)])
    rows = flat.shape[0] // _TILE
    lanes = _TILE // _GROUP
    gr = flat.reshape(rows, lanes, _GROUP).swapaxes(1, 2)
    rr = res.reshape(rows, lanes, _GROUP).swapaxes(1, 2)
    grid = (max(1, (rows + block_rows - 1) // block_rows),)
    br = min(block_rows, rows)
    thr = jnp.asarray([[threshold]], gr.dtype)
    interpret = jax.default_backend() != "tpu"
    if interpret:
        thr_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    else:
        # scalar operands must live in SMEM on TPU — Mosaic cannot lower a
        # direct load from an ANY-space ref
        from jax.experimental.pallas import tpu as pltpu
        thr_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    packed, newr = pl.pallas_call(
        _qp_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((br, _GROUP, lanes), lambda i: (i, 0, 0)),
                  pl.BlockSpec((br, _GROUP, lanes), lambda i: (i, 0, 0)),
                  thr_spec],
        out_specs=[pl.BlockSpec((br, lanes), lambda i: (i, 0)),
                   pl.BlockSpec((br, _GROUP, lanes), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, lanes), jnp.uint32),
                   jax.ShapeDtypeStruct((rows, _GROUP, lanes), gr.dtype)],
        interpret=interpret,
    )(gr, rr, thr)
    newr = newr.swapaxes(1, 2).reshape(-1)[:n].reshape(shape)
    return packed.reshape(-1)[: (n + _GROUP - 1) // _GROUP], newr


# ---------------------------------------------------------------------------
# Quantized collective
# ---------------------------------------------------------------------------

def quantized_psum(x, axis_name, threshold, residual):
    """Inside shard_map: all-reduce with a 2-bit wire format. Each member
    quantizes (with its own error-feedback residual), all_gathers the
    PACKED payload (1/16 of the float bytes over ICI/DCN), and
    dequantize-sums locally. Returns (sum, new_residual)."""
    n = x.size
    packed, new_res = quantize_pack(x, residual, threshold)
    allp = lax.all_gather(packed, axis_name)             # (W, ceil(n/16))
    deq = jax.vmap(lambda p: two_bit_unpack(p, n, threshold, x.dtype))(allp)
    return jnp.sum(deq, axis=0).reshape(x.shape), new_res


def quantized_allreduce(x, mesh, threshold, residual=None, axis=None):
    """Whole-array entry: replicated x (and residual) -> (sum over the
    axis members' quantized contributions, new residual). With a
    replicated input every member contributes the same value — the
    multi-process kvstore instead passes per-process values via its
    collective mesh (kvstore._axis0_packed_sum)."""
    from jax.sharding import PartitionSpec as P

    if residual is None:
        residual = jnp.zeros_like(x)
    axis = axis or mesh.axis_names[0]

    def inner(xx, rr):
        return quantized_psum(xx, axis, threshold, rr)

    return shard_map(inner, mesh, in_specs=(P(), P()),
                     out_specs=(P(), P()))(x, residual)
