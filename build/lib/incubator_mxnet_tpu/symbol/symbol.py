"""Symbol: deferred graph composition over the op registry.

Reference behavior being matched (python/mxnet/symbol/symbol.py +
src/c_api/c_api_symbolic.cc):
  * compose ops into a DAG with auto-created parameter variables
    (`sym.FullyConnected(data, num_hidden=128)` creates fc0_weight/fc0_bias),
  * `list_arguments` / `list_outputs` / `list_auxiliary_states`,
  * `infer_shape` with bidirectional parameter-shape inference,
  * MXNet-compatible JSON save/load (both the 1.x `attrs` format and the
    legacy v0 `param`/`attr` format upgraded by src/nnvm/legacy_json_util.cc),
  * `eval`, `bind`, `simple_bind` (executor.py compiles via jax.jit).

TPU-native redesign: no NNVM; node attrs hold real Python values; shape/type
inference is jax.eval_shape over the same op functions the eager path runs, so
symbolic and imperative semantics can never drift (the reference maintains two
dispatch paths into shared kernels for the same guarantee).
"""
from __future__ import annotations

import ast
import inspect
import json
import threading

import numpy as _np

from ..base import MXNetError, dtype_name, dtype_np
from ..ops import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones"]


# ---------------------------------------------------------------------------
# op metadata the symbol layer needs beyond the OpDef
# ---------------------------------------------------------------------------

# inputs that are auxiliary states (not learned via gradient; reference:
# mutable inputs declared by the op, surfaced as list_auxiliary_states)
AUX_INPUTS = {
    "BatchNorm": ("moving_mean", "moving_var"),
    "BatchNormV1": ("moving_mean", "moving_var"),
    "SyncBatchNorm": ("moving_mean", "moving_var"),
}

# ops returning tuples where composition should see only output 0
# (reference: FNumVisibleOutputs — BatchNorm exposes out, hides mean/var)
_VISIBLE_ONE = {"BatchNorm", "SyncBatchNorm"}


def _num_outputs(op, attrs):
    """Worst-case output count of an op node (full tuple arity)."""
    name = op.name
    if name in ("BatchNorm", "SyncBatchNorm"):
        return 3
    if name == "LayerNorm":
        return 3 if attrs.get("output_mean_var") else 1
    if name in ("Moments", "moments"):
        return 2
    if name in ("split", "SliceChannel"):
        n = int(attrs.get("num_outputs", 1))
        return n if n > 1 else 1
    if name == "split_v2":
        ios = attrs.get("indices_or_sections", 1)
        return (len(ios) + 1) if isinstance(ios, (tuple, list)) else int(ios)
    if name == "RNN":
        return 3 if attrs.get("state_outputs") else 1
    return 1


def _visible_outputs(op, attrs):
    if op.name in _VISIBLE_ONE:
        return 1
    return _num_outputs(op, attrs)


_sig_cache: dict = {}


def _op_signature(op):
    """(array_arg_names, has_varargs, kw_names) from the op function."""
    got = _sig_cache.get(op.name)
    if got is None:
        sig = inspect.signature(op.fn)
        arr, kw, varargs = [], set(), False
        for p in sig.parameters.values():
            if p.kind == inspect.Parameter.VAR_POSITIONAL:
                varargs = True
            elif p.kind == inspect.Parameter.POSITIONAL_OR_KEYWORD:
                arr.append((p.name, p.default is inspect.Parameter.empty))
            elif p.kind == inspect.Parameter.KEYWORD_ONLY:
                kw.add(p.name)
        got = (arr, varargs, kw)
        _sig_cache[op.name] = got
    return got


# parameter-shape inference rules: fn(attrs, in_shapes_by_name) -> {arg: shape}
# This is the forward half of the reference's bidirectional infer_shape
# (src/executor/infer_graph_attr_pass.cc) — enough to bind real models from
# data shapes alone.
def _infer_fc(attrs, s):
    d = s.get("data")
    if d is None:
        return {}
    nh = int(attrs.get("num_hidden", 0))
    ind = int(_np.prod(d[1:])) if attrs.get("flatten", True) else d[-1]
    out = {"weight": (nh, ind)}
    if not attrs.get("no_bias", False):
        out["bias"] = (nh,)
    return out


def _infer_conv(attrs, s):
    d = s.get("data")
    if d is None:
        return {}
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    kernel = tuple(attrs.get("kernel", ()))
    out = {"weight": (nf, d[1] // ng) + kernel}
    if not attrs.get("no_bias", False):
        out["bias"] = (nf,)
    return out


def _infer_deconv(attrs, s):
    d = s.get("data")
    if d is None:
        return {}
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    kernel = tuple(attrs.get("kernel", ()))
    out = {"weight": (d[1], nf // ng) + kernel}
    if not attrs.get("no_bias", False):
        out["bias"] = (nf,)
    return out


def _infer_norm(attrs, s):
    d = s.get("data")
    if d is None:
        return {}
    ax = int(attrs.get("axis", 1))
    c = d[ax % len(d)]
    return {k: (c,) for k in ("gamma", "beta", "moving_mean", "moving_var")}


def _infer_lnorm(attrs, s):
    d = s.get("data")
    if d is None:
        return {}
    ax = int(attrs.get("axis", -1))
    c = d[ax % len(d)]
    return {"gamma": (c,), "beta": (c,)}


def _infer_embedding(attrs, s):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


INFER_PARAM_SHAPES = {
    "FullyConnected": _infer_fc,
    "Convolution": _infer_conv,
    "Deconvolution": _infer_deconv,
    "BatchNorm": _infer_norm,
    "SyncBatchNorm": _infer_norm,
    "InstanceNorm": _infer_lnorm,
    "LayerNorm": _infer_lnorm,
    # gamma/beta are per-GROUP: shape (num_groups,), reference
    # group_norm-inl.h:163 + gluon basic_layers.py:690-695
    "GroupNorm": lambda a, s: {"gamma": (int(a.get("num_groups", 1)),),
                               "beta": (int(a.get("num_groups", 1)),)},
    "Embedding": _infer_embedding,
}


# ---------------------------------------------------------------------------
# graph node
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("op", "name", "attrs", "extra", "inputs", "arg_names")

    def __init__(self, op, name, attrs, inputs, extra=None, arg_names=None):
        self.op = op            # OpDef or None for a variable
        self.name = name
        self.attrs = attrs      # python-typed op params
        self.extra = extra or {}  # non-param attrs (lr_mult, __shape__, ...)
        self.inputs = inputs    # list[(node, out_index)]
        # names of the array args each input binds to (for aux detection)
        self.arg_names = arg_names or []


class _NameManager:
    _lock = threading.Lock()
    _counts: dict = {}

    @classmethod
    def next(cls, hint):
        with cls._lock:
            i = cls._counts.get(hint, 0)
            cls._counts[hint] = i + 1
        return f"{hint}{i}"


def _topo(entries):
    """Iterative post-order over node graph; returns nodes in topo order."""
    seen, order, stack = set(), [], [(n, False) for n, _ in reversed(entries)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for inp, _ in reversed(node.inputs):
            if id(inp) not in seen:
                stack.append((inp, False))
    return order


# ---------------------------------------------------------------------------
# Symbol
# ---------------------------------------------------------------------------

class Symbol:
    """A handle on one or more graph outputs (reference symbol.py Symbol)."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # [(node, out_idx)]

    # -- identity -----------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) != 1:
            return None
        return self._outputs[0][0].name

    def __repr__(self):
        return f"<Symbol {self.name or 'group'}>"

    def __len__(self):
        return len(self._visible_entries())

    def __iter__(self):
        ents = self._visible_entries()
        return iter(Symbol([e]) for e in ents)

    def _visible_entries(self):
        ents = []
        for node, idx in self._outputs:
            ents.append((node, idx))
        return ents

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index in names:
                return Symbol([self._outputs[names.index(index)]])
            # allow bare node name
            for i, (node, idx) in enumerate(self._outputs):
                if node.name == index:
                    return Symbol([self._outputs[i]])
            raise MXNetError(f"no output named {index!r} (have {names})")
        return Symbol([self._outputs[index]])

    # -- attrs --------------------------------------------------------------
    def attr(self, key):
        node = self._outputs[0][0]
        v = node.extra.get(key)
        if v is None and key in node.attrs:
            v = str(node.attrs[key])
        return v

    def list_attr(self):
        node = self._outputs[0][0]
        out = {k: str(v) for k, v in node.attrs.items()}
        out.update({k: str(v) for k, v in node.extra.items()})
        return out

    def attr_dict(self):
        out = {}
        for node in _topo(self._outputs):
            d = {k: str(v) for k, v in node.attrs.items()}
            d.update({k: str(v) for k, v in node.extra.items()})
            if d:
                out[node.name] = d
        return out

    def _set_attr(self, **kwargs):
        self._outputs[0][0].extra.update(kwargs)

    # -- listing ------------------------------------------------------------
    def _aux_var_ids(self):
        aux = set()
        for node in _topo(self._outputs):
            if node.op is None:
                continue
            aux_names = AUX_INPUTS.get(node.op.name, ())
            for (inp, _), aname in zip(node.inputs, node.arg_names):
                if inp.op is None and aname in aux_names:
                    aux.add(id(inp))
        return aux

    def list_arguments(self):
        aux = self._aux_var_ids()
        return [n.name for n in _topo(self._outputs)
                if n.op is None and id(n) not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_var_ids()
        return [n.name for n in _topo(self._outputs)
                if n.op is None and id(n) in aux]

    def list_inputs(self):
        return [n.name for n in _topo(self._outputs) if n.op is None]

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.op is None:
                out.append(node.name)
            else:
                nout = _num_outputs(node.op, node.attrs)
                suffix = "output" if nout == 1 or idx == 0 else f"output{idx}"
                out.append(f"{node.name}_{suffix}")
        return out

    def get_internals(self):
        ents = []
        for node in _topo(self._outputs):
            if node.op is None:
                ents.append((node, 0))
            else:
                for i in range(_visible_outputs(node.op, node.attrs)):
                    ents.append((node, i))
        return Symbol(ents)

    def get_children(self):
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol([(n, i) for n, i in node.inputs])

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise
        except Exception as e:  # mirror reference error surface
            raise MXNetError(f"infer_shape error: {e}") from e

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax

        if args:
            kwargs = dict(zip(self.list_arguments(), args))
        known = {k: tuple(v) for k, v in kwargs.items() if v is not None}
        dtypes = {}
        shapes, _ = self._run_inference(known, dtypes, partial)
        if shapes is None:
            return None, None, None
        args_order = self.list_arguments()
        aux_order = self.list_auxiliary_states()
        arg_shapes = [shapes.get(n) for n in args_order]
        aux_shapes = [shapes.get(n) for n in aux_order]
        out_shapes = [shapes[f"__out__{i}"] for i in range(len(self._outputs))]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        if args:
            kwargs = dict(zip(self.list_arguments(), args))
        dtypes = {k: dtype_np(v) for k, v in kwargs.items() if v is not None}
        args_order = self.list_arguments()
        aux_order = self.list_auxiliary_states()
        try:
            _, types = self._run_inference({}, dtypes, False, want_types=True)
        except MXNetError:
            # no shapes available: fall back to uniform-dtype propagation
            # (the reference's type inference is shape-free; ours rides
            # eval_shape, so without shapes we assume one floating dtype)
            uni = next(iter(dtypes.values()), _np.float32)
            return ([dtypes.get(n, uni) for n in args_order],
                    [uni] * len(self._outputs),
                    [dtypes.get(n, uni) for n in aux_order])
        return ([types.get(n) for n in args_order],
                [types[f"__out__{i}"] for i in range(len(self._outputs))],
                [types.get(n) for n in aux_order])

    def _run_inference(self, known_shapes, known_dtypes, partial,
                       want_types=False):
        """Walk the graph with jax.eval_shape, inferring variable shapes from
        per-op parameter rules as they become needed."""
        import jax

        var_shape = dict(known_shapes)
        var_dtype = dict(known_dtypes)
        entry_aval = {}

        for node in _topo(self._outputs):
            if node.op is None:
                shp = var_shape.get(node.name)
                if shp is None and "__shape__" in node.extra:
                    shp = tuple(node.extra["__shape__"])
                    var_shape[node.name] = shp
                dt = var_dtype.get(node.name)
                if dt is None and "__dtype__" in node.extra:
                    dt = dtype_np(node.extra["__dtype__"])
                entry_aval[(id(node), 0)] = (shp, dt or _np.float32)
                continue

            # try to infer still-unknown variable inputs from known ones
            rule = INFER_PARAM_SHAPES.get(node.op.name)
            in_shapes = {}
            for (inp, oi), aname in zip(node.inputs, node.arg_names):
                av = entry_aval.get((id(inp), oi))
                if av and av[0] is not None:
                    in_shapes[aname] = av[0]
            if rule is not None:
                inferred = rule(node.attrs, in_shapes)
                for (inp, oi), aname in zip(node.inputs, node.arg_names):
                    if inp.op is None and aname in inferred:
                        prev = var_shape.get(inp.name)
                        got = tuple(int(x) for x in inferred[aname])
                        if prev is not None and tuple(prev) != got:
                            raise MXNetError(
                                f"shape mismatch for {inp.name}: bound "
                                f"{prev} but inferred {got} at {node.name}")
                        if prev is None:
                            var_shape[inp.name] = got
                            entry_aval[(id(inp), 0)] = (
                                got, entry_aval[(id(inp), 0)][1])

            ins = []
            missing = False
            for (inp, oi) in node.inputs:
                shp, dt = entry_aval[(id(inp), oi)]
                if shp is None:
                    missing = True
                    break
                ins.append(jax.ShapeDtypeStruct(tuple(shp), dt))
            if missing:
                if partial:
                    n = _num_outputs(node.op, node.attrs)
                    for i in range(n):
                        entry_aval[(id(node), i)] = (None, None)
                    continue
                unk = [inp.name for inp, oi in node.inputs
                       if entry_aval[(id(inp), oi)][0] is None]
                raise MXNetError(
                    f"infer_shape: cannot infer shapes of {unk} needed by "
                    f"op {node.op.name} '{node.name}'; provide them explicitly")

            kwargs = dict(node.attrs)
            if node.op.train_aware:
                kwargs.setdefault("training", False)
            fn = node.op.fn
            if node.op.stateful:
                key_aval = jax.ShapeDtypeStruct((2,), _np.uint32)
                out = jax.eval_shape(
                    lambda k, *xs, _f=fn, _kw=kwargs: _f(*xs, rng=k, **_kw),
                    key_aval, *ins)
            else:
                out = jax.eval_shape(lambda *xs, _f=fn, _kw=kwargs: _f(*xs, **_kw),
                                     *ins)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for i, o in enumerate(outs):
                entry_aval[(id(node), i)] = (tuple(o.shape), o.dtype)

        shapes, types = {}, {}
        for name, node in [(n.name, n) for n in _topo(self._outputs)
                           if n.op is None]:
            av = entry_aval[(id(node), 0)]
            shapes[name] = tuple(av[0]) if av[0] is not None else None
            types[name] = av[1]
        for i, (node, oi) in enumerate(self._outputs):
            av = entry_aval[(id(node), oi)]
            shapes[f"__out__{i}"] = tuple(av[0]) if av[0] is not None else None
            types[f"__out__{i}"] = av[1]
        return shapes, types if want_types else None

    # -- evaluation ---------------------------------------------------------
    def _build_eval(self, training=False):
        """Returns fn(bindings: dict[str, jax.Array], rng) -> list[jax.Array]
        plus the list of (node, stat_index) BatchNorm batch stats for aux
        updates (the reference op mutates aux in the kernel; we return the
        batch stats functionally)."""
        order = _topo(self._outputs)
        bn_nodes = [n for n in order
                    if n.op is not None and n.op.name in AUX_INPUTS]

        def run(bindings, rng=None):
            import jax
            cache = {}
            key = rng

            def key_next():
                nonlocal key
                if key is None:
                    from ..ndarray import random as _rnd
                    return _rnd.next_key()
                key, sub = jax.random.split(key)
                return sub

            for node in order:
                if node.op is None:
                    if node.name not in bindings:
                        raise MXNetError(f"unbound variable {node.name!r}")
                    cache[id(node)] = (bindings[node.name],)
                    continue
                ins = [cache[id(inp)][oi] for inp, oi in node.inputs]
                kwargs = dict(node.attrs)
                if _registry.AMP_HOOK is not None:
                    ins = _registry.AMP_HOOK(node.op.name, ins, kwargs)
                if node.op.train_aware:
                    kwargs.setdefault("training", training)
                if node.op.stateful:
                    kwargs["rng"] = key_next()
                res = node.op.fn(*ins, **kwargs)
                cache[id(node)] = tuple(res) if isinstance(res, (tuple, list)) \
                    else (res,)
            outs = [cache[id(n)][i] for n, i in self._outputs]
            stats = {}
            for n in bn_nodes:
                got = cache[id(n)]
                if len(got) >= 3:
                    # (out, batch_mean, batch_var) per ops/nn_ops.py BatchNorm
                    stats[n.name] = (got[1], got[2])
            return outs, stats

        return run

    def eval_dict(self, bindings, training=None):
        """Evaluate eagerly with a name->NDArray dict; returns NDArray list
        (single NDArray if one output)."""
        from .. import autograd
        from ..ndarray import NDArray

        if training is None:
            training = autograd.is_training()
        vals = {k: (v._data if isinstance(v, NDArray) else v)
                for k, v in bindings.items()}
        run = self._build_eval(training=training)
        outs, _ = run(vals)
        outs = [NDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def eval(self, ctx=None, **kwargs):
        out = self.eval_dict(kwargs)
        return out if isinstance(out, list) else [out]

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from ..executor import Executor
        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **shapes):
        from ..executor import Executor
        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    type_dict=type_dict, **shapes)

    # -- serialization ------------------------------------------------------
    def tojson(self):
        order = _topo(self._outputs)
        idx = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            attrs = {k: _attr_str(v) for k, v in n.attrs.items()}
            attrs.update({k: _attr_str(v) for k, v in n.extra.items()})
            entry = {
                "op": "null" if n.op is None else n.op.name,
                "name": n.name,
                "inputs": [[idx[id(i)], oi, 0] for i, oi in n.inputs],
            }
            if attrs:
                entry["attrs"] = attrs
            nodes.append(entry)
        arg_nodes = [i for i, n in enumerate(order) if n.op is None]
        heads = [[idx[id(n)], oi, 0] for n, oi in self._outputs]
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(order) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10500]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- composition sugar --------------------------------------------------
    def __call__(self, *args, **kwargs):
        raise MXNetError("composition via __call__ is not supported; "
                         "pass symbols to sym.<Op>(...) directly")

    def _entry(self):
        if len(self._outputs) != 1:
            raise MXNetError("operation requires a single-output symbol")
        return self._outputs[0]

    def __add__(self, other):
        return _scalar_or_broadcast(self, other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return _scalar_or_broadcast(self, other, "broadcast_sub", "_sub_scalar")

    def __rsub__(self, other):
        return _scalar_op(self, other, "_rsub_scalar")

    def __mul__(self, other):
        return _scalar_or_broadcast(self, other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _scalar_or_broadcast(self, other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _scalar_op(self, other, "_rdiv_scalar")

    def __pow__(self, other):
        return _scalar_or_broadcast(self, other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _scalar_op(self, -1.0, "_mul_scalar")


def _method(opname, self, *args, **kwargs):
    return _create(_registry.get_op(opname), (self,) + args, kwargs)


for _m, _op in [("reshape", "reshape"), ("transpose", "transpose"),
                ("flatten", "flatten"), ("sum", "sum"), ("mean", "mean"),
                ("max", "max"), ("min", "min"), ("prod", "prod"),
                ("astype", "cast"), ("slice_axis", "slice_axis"),
                ("expand_dims", "expand_dims"), ("squeeze", "squeeze"),
                ("clip", "clip"), ("abs", "abs"), ("exp", "exp"),
                ("log", "log"), ("sqrt", "sqrt"), ("square", "square"),
                ("relu", "relu"), ("sigmoid", "sigmoid"), ("tanh", "tanh"),
                ("softmax", "softmax"), ("log_softmax", "log_softmax"),
                ("dot", "dot"), ("argmax", "argmax"), ("argmin", "argmin"),
                ("take", "take"), ("tile", "tile"), ("repeat", "repeat"),
                ("split", "split"), ("swapaxes", "swapaxes"),
                ("broadcast_to", "broadcast_to"), ("one_hot", "one_hot")]:
    def _bound(self, *a, _op=_op, **k):
        return _method(_op, self, *a, **k)

    _bound.__name__ = _m
    setattr(Symbol, _m, _bound)


def _scalar_or_broadcast(sym, other, broadcast_op, scalar_op):
    if isinstance(other, Symbol):
        return _create(_registry.get_op(broadcast_op), (sym, other), {})
    return _scalar_op_impl(sym, other, scalar_op)


def _scalar_op(sym, other, scalar_op):
    return _scalar_op_impl(sym, other, scalar_op)


def _scalar_op_impl(sym, scalar, opname):
    return _create(_registry.get_op(opname), (sym,), {"scalar": float(scalar)})


def _attr_str(v):
    if isinstance(v, str):
        return v
    return str(v)


def _coerce_attr(v):
    """Parse a stringified attr back to a python value (MXNet JSON stores all
    attrs as strings)."""
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------

def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (reference symbol.py var/Variable)."""
    extra = dict(attr or {})
    extra.update(kwargs)
    if shape is not None:
        extra["__shape__"] = tuple(shape)
    if dtype is not None:
        extra["__dtype__"] = dtype_name(dtype_np(dtype))
    if lr_mult is not None:
        extra["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        extra["__wd_mult__"] = wd_mult
    if init is not None:
        extra["__init__"] = init if isinstance(init, str) else \
            getattr(init, "dumps", lambda: str(init))()
    node = _Node(None, name, {}, [], extra=extra)
    return Symbol([(node, 0)])


Variable = var


def Group(symbols):
    ents = []
    for s in symbols:
        ents.extend(s._outputs)
    return Symbol(ents)


def zeros(shape, dtype="float32", name=None, **kwargs):
    return _create(_registry.get_op("_zeros"), (),
                   {"shape": tuple(shape), "dtype": dtype}, name=name)


def ones(shape, dtype="float32", name=None, **kwargs):
    return _create(_registry.get_op("_ones"), (),
                   {"shape": tuple(shape), "dtype": dtype}, name=name)


def _create(op, args, kwargs, name=None):
    """Compose an op node from Symbol args + python attrs, auto-creating
    missing parameter variables (reference c_api_symbolic.cc MXSymbolCompose +
    NameManager python/mxnet/name.py)."""
    arr_args, varargs, kw_names = _op_signature(op)
    kwargs = dict(kwargs)
    name = name or kwargs.pop("name", None)
    attr = kwargs.pop("attr", None)

    # split kwargs into symbol inputs vs op params
    sym_kwargs = {}
    attrs = {}
    extra = dict(attr or {})
    for k, v in list(kwargs.items()):
        if isinstance(v, Symbol):
            sym_kwargs[k] = v
        elif k in kw_names:
            attrs[k] = v
        elif k in [a for a, _ in arr_args]:
            if v is None:
                continue
            raise MXNetError(f"{op.name}: argument {k!r} must be a Symbol, "
                             f"got {type(v).__name__}")
        else:
            extra[k] = v

    name = name or _NameManager.next(op.name.lower().lstrip("_"))

    inputs = []
    arg_names_used = []

    if varargs:
        for i, a in enumerate(args):
            if not isinstance(a, Symbol):
                raise MXNetError(f"{op.name}: positional args must be Symbols")
            inputs.append(a._entry_for_compose())
            arg_names_used.append(f"arg{i}")
        if "num_args" in kw_names:
            attrs.setdefault("num_args", len(inputs))
    else:
        # positional symbols fill array-arg slots in order
        pos = list(args)
        for aname, required in arr_args:
            s = None
            if aname in sym_kwargs:
                s = sym_kwargs.pop(aname)
            elif pos:
                nxt = pos[0]
                if isinstance(nxt, Symbol):
                    s = pos.pop(0)
                elif nxt is None:
                    # explicit "no input" slot (bias=None when use_bias=False)
                    pos.pop(0)
                    continue
            if s is None:
                # auto-create a trailing parameter variable when needed
                if required or _wants_auto_var(op, aname, attrs):
                    s = var(f"{name}_{aname}")
                else:
                    continue
            inputs.append(s._entry_for_compose())
            arg_names_used.append(aname)
        if pos:
            raise MXNetError(f"{op.name}: too many positional args")
        if sym_kwargs:
            raise MXNetError(f"{op.name}: unknown symbol kwargs "
                             f"{sorted(sym_kwargs)}")

    if op.train_aware:
        # symbols carry no train-mode attr — the mode comes from the
        # executor's is_train at run time (reference: OpContext.is_train)
        attrs.pop("training", None)

    node = _Node(op, name, attrs, inputs, extra=extra,
                 arg_names=arg_names_used)
    n_vis = _visible_outputs(op, attrs)
    return Symbol([(node, i) for i in range(n_vis)])


def _wants_auto_var(op, aname, attrs):
    """Should an omitted optional array input become an auto variable?
    Mirrors the reference convention: bias exists unless no_bias."""
    if aname == "bias":
        return not attrs.get("no_bias", False)
    if aname == "gamma" and op.name == "LeakyReLU":
        return attrs.get("act_type") == "prelu"
    return False


# patch Symbol composition entry helper
def _entry_for_compose(self):
    if len(self._outputs) != 1:
        raise MXNetError(
            "cannot use a multi-output symbol as an op input; select one "
            "output with sym[i]")
    return self._outputs[0]


Symbol._entry_for_compose = _entry_for_compose


def _make_sym_creator(opdef):
    def creator(*args, **kwargs):
        return _create(opdef, args, kwargs)

    creator.__name__ = opdef.name
    creator.__doc__ = opdef.fn.__doc__
    return creator


# ---------------------------------------------------------------------------
# JSON load (MXNet-compatible; handles 1.x "attrs" and legacy v0 "param")
# ---------------------------------------------------------------------------

def load_json(json_str):
    d = json.loads(json_str)
    if "nodes" not in d:
        raise MXNetError("not a symbol json: missing 'nodes'")
    nodes = []
    for nd_ in d["nodes"]:
        opname = nd_["op"]
        raw_attrs = {}
        # modern: "attrs"; legacy v0: "param" (op params) + "attr" (user attrs)
        raw_attrs.update(nd_.get("param") or {})
        raw_attrs.update(nd_.get("attrs") or {})
        user_attrs = dict(nd_.get("attr") or {})
        if opname == "null":
            node = _Node(None, nd_["name"], {}, [],
                         extra={k: _coerce_attr(v) for k, v in
                                {**raw_attrs, **user_attrs}.items()})
            nodes.append(node)
            continue
        op = _registry.get_op(opname)
        arr_args, varargs, kw_names = _op_signature(op)
        attrs, extra = {}, {}
        for k, v in {**raw_attrs, **user_attrs}.items():
            if k in kw_names:
                attrs[k] = _coerce_attr(v)
            else:
                extra[k] = _coerce_attr(v)
        inputs = [(nodes[i[0]], i[1]) for i in nd_["inputs"]]
        if varargs:
            argnames = [f"arg{i}" for i in range(len(inputs))]
        else:
            argnames = [a for a, _ in arr_args][:len(inputs)]
            # legacy v0 JSON omits auxiliary inputs (BatchNorm moving stats
            # predate their appearance in the graph); materialize them
            for aname, required in arr_args[len(inputs):]:
                if required:
                    vnode = _Node(None, f"{nd_['name']}_{aname}", {}, [])
                    inputs.append((vnode, 0))
                    argnames.append(aname)
        node = _Node(op, nd_["name"], attrs, inputs, extra=extra,
                     arg_names=argnames)
        nodes.append(node)
    heads = d.get("heads") or [[len(nodes) - 1, 0, 0]]
    return Symbol([(nodes[h[0]], h[1] if len(h) > 1 else 0) for h in heads])


def fromjson(json_str):
    return load_json(json_str)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
