"""`mx.sym` namespace: symbolic graph composition.

Reference: python/mxnet/symbol/ (7,527 LoC) over the NNVM C graph
(src/c_api/c_api_symbolic.cc). Here a Symbol is a pure-Python DAG over the
SAME op registry the eager path uses; `bind` compiles the graph with jax.jit
instead of the reference's GraphExecutor (src/executor/graph_executor.cc:388).
"""
from __future__ import annotations

from .symbol import (Group, Symbol, Variable, load, load_json, var,
                     zeros, ones)

from ..ops import registry as _registry
from . import symbol as _symbol_mod


def __getattr__(name):
    if name in _registry.OPS:
        w = _symbol_mod._make_sym_creator(_registry.OPS.get(name))
        globals()[name] = w
        return w
    raise AttributeError(f"module 'symbol' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + _registry.OPS.keys()))
