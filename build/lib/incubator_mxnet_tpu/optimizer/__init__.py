from .optimizer import *  # noqa: F401,F403
from . import optimizer  # noqa: F401
