#!/usr/bin/env python
"""Sorting digit sequences with a bidirectional LSTM (reference
example/bi-lstm-sort/sort_io.py + lstm_sort.py).

The classic seq2seq-lite task: input is a sequence of random digits,
target is the same digits sorted. A BidirectionalCell over LSTM cells
reads the whole sequence both ways and a per-step classifier emits the
sorted digit at each position — the same architecture the reference
trains, on the same synthetic task.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_batch(rng, n, seq_len, vocab):
    x = rng.randint(0, vocab, (n, seq_len))
    y = np.sort(x, axis=1)
    return x.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seq-len", type=int, default=6)
    ap.add_argument("--vocab", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batches-per-epoch", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--min-acc", type=float, default=0.7,
                    help="per-digit accuracy gate (chance = 1/vocab)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)

    embed = gluon.nn.Embedding(args.vocab, 16)
    bilstm = gluon.rnn.BidirectionalCell(
        gluon.rnn.LSTMCell(args.hidden),
        gluon.rnn.LSTMCell(args.hidden))
    head = gluon.nn.Dense(args.vocab, flatten=False)
    for blk in (embed, bilstm, head):
        blk.initialize(mx.init.Xavier())
    params = gluon.parameter.ParameterDict()
    for blk in (embed, bilstm, head):
        params.update(blk.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(xb):
        e = embed(xb)                                  # (B, T, E)
        outs, _ = bilstm.unroll(args.seq_len, e, merge_outputs=True)
        return head(outs)                              # (B, T, vocab)

    accs = []
    for ep in range(args.epochs):
        tot, nb = 0.0, 0
        for _ in range(args.batches_per_epoch):
            xb_np, yb_np = make_batch(rng, args.batch_size, args.seq_len,
                                      args.vocab)
            xb, yb = nd.array(xb_np), nd.array(yb_np)
            with autograd.record():
                logits = forward(xb)
                loss = loss_fn(logits.reshape((-1, args.vocab)),
                               yb.reshape((-1,)))
            loss.backward()
            trainer.step(args.batch_size)
            tot += float(loss.mean().asnumpy())
            nb += 1
        xe, ye = make_batch(rng, 256, args.seq_len, args.vocab)
        pred = forward(nd.array(xe)).asnumpy().argmax(-1)
        acc = (pred == ye).mean()
        accs.append(acc)
        if ep % 2 == 0:
            print(f"epoch {ep}: loss {tot / nb:.4f}  "
                  f"per-digit acc {acc:.3f}")

    print(f"per-digit accuracy: first {accs[0]:.3f} -> last {accs[-1]:.3f}")
    assert accs[-1] > args.min_acc, accs[-1]
    sample_x, sample_y = make_batch(rng, 1, args.seq_len, args.vocab)
    sample_p = forward(nd.array(sample_x)).asnumpy().argmax(-1)
    print("input ", sample_x[0].astype(int).tolist())
    print("sorted", sample_p[0].astype(int).tolist(),
          "(truth", sample_y[0].astype(int).tolist(), ")")
    print("BILSTM_SORT_OK", accs[-1])


if __name__ == "__main__":
    main()
