#!/usr/bin/env python
"""Multivariate time-series forecasting (reference
example/multivariate_time_series/src/lstnet.py — LSTNet on the
electricity dataset: convolutional feature extraction over a window of
all series, recurrent aggregation, autoregressive highway).

Synthetic data: coupled sinusoids + noise where each series is a lagged
mixture of the others — so the forecaster must exploit CROSS-series
structure, not just extrapolate one curve. The model keeps LSTNet's
shape (Conv1D over the window -> GRU -> dense forecast, plus a linear
autoregressive bypass) and is scored by relative absolute error (RAE)
against the naive last-value forecast, which it must beat decisively.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_SERIES = 4
WINDOW = 24


def make_series(rng, length):
    t = np.arange(length)
    base = np.stack([np.sin(2 * np.pi * t / p) for p in (12, 17, 23, 31)])
    mix = rng.rand(N_SERIES, N_SERIES) * 0.5 + 0.5 * np.eye(N_SERIES)
    y = mix @ base + 0.05 * rng.randn(N_SERIES, length)
    return y.astype(np.float32)            # (S, T)


def windows(y, horizon=1):
    S, T = y.shape
    X, Y = [], []
    for t in range(WINDOW, T - horizon):
        X.append(y[:, t - WINDOW:t].T)     # (WINDOW, S)
        Y.append(y[:, t + horizon - 1])    # (S,)
    return np.stack(X), np.stack(Y)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    series = make_series(rng, 2200)
    X, Y = windows(series)
    n_train = int(len(X) * 0.8)
    Xtr, Ytr = X[:n_train], Y[:n_train]
    Xte, Yte = X[n_train:], Y[n_train:]

    class LSTNetLite(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.conv = gluon.nn.Conv1D(16, 3, padding=1,
                                            activation="relu")
                self.gru = gluon.rnn.GRU(32, layout="NTC")
                self.fc = gluon.nn.Dense(N_SERIES)
                self.ar = gluon.nn.Dense(N_SERIES)   # highway bypass

        def hybrid_forward(self, F, x):
            # x: (B, WINDOW, S) -> conv over time needs (B, C=S, T)
            c = self.conv(F.transpose(x, axes=(0, 2, 1)))     # (B, 16, T)
            h = self.gru(F.transpose(c, axes=(0, 2, 1)))      # (B, T, 32)
            last = F.slice_axis(h, axis=1, begin=-1, end=None) \
                    .reshape((0, -1))
            ar_in = F.slice_axis(x, axis=1, begin=-8, end=None) \
                     .reshape((0, -1))
            return self.fc(last) + self.ar(ar_in)

    net = LSTNetLite()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    l2 = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            with autograd.record():
                loss = l2(net(nd.array(Xtr[idx])),
                          nd.array(Ytr[idx])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} mse {tot / (n // args.batch_size):.5f}")

    pred = net(nd.array(Xte)).asnumpy()
    rae = np.abs(pred - Yte).sum() / np.abs(Xte[:, -1, :] - Yte).sum()
    print(f"relative absolute error vs naive last-value: {rae:.3f}")
    assert rae < 0.7, rae                 # must clearly beat persistence
    print("TIMESERIES_OK")


if __name__ == "__main__":
    main()
