#!/usr/bin/env python
"""SSD detection TRAINING end-to-end (reference example/ssd/train.py).

Exercises the full detection training stack on synthetic data:
  multibox_prior  -> anchors over the feature map
  multibox_target -> per-anchor cls/box targets with hard-negative mining
  SmoothL1 + SoftmaxCrossEntropy joint loss, trained with gluon.Trainer
  MultiBoxDetection -> decoded detections from the trained model

The synthetic task plants one axis-aligned box per image whose position
is derivable from the image content (a bright rectangle), so the loss
provably decreases and the decoded detection converges onto the planted
box. The whole step (feature extraction, target assignment, loss) is
hybridized into one compiled graph — target assignment is an op, exactly
like the reference's C++ MultiBoxTarget, not a python loop.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_batch(rng, batch, size):
    """Images with one bright rectangle; label row [cls, x1 y1 x2 y2]."""
    x = rng.rand(batch, 3, size, size).astype(np.float32) * 0.1
    labels = np.full((batch, 1, 5), -1.0, np.float32)
    for i in range(batch):
        w = rng.randint(size // 4, size // 2)
        h = rng.randint(size // 4, size // 2)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        x[i, :, y0:y0 + h, x0:x0 + w] += 0.9
        labels[i, 0] = [0, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + h) / size]
    return x, labels


class ToySSD:
    def __init__(self, mx, gluon, num_classes):
        self.num_classes = num_classes
        self.backbone = gluon.nn.HybridSequential()
        for ch in (16, 32, 32):
            self.backbone.add(gluon.nn.Conv2D(ch, 3, padding=1, strides=2,
                                              activation="relu"))
        # MultiBoxPrior convention: len(sizes)+len(ratios)-1 per cell
        self.anchors_per_cell = 3
        self.cls_head = gluon.nn.Conv2D(
            (num_classes + 1) * self.anchors_per_cell, 1)
        self.box_head = gluon.nn.Conv2D(4 * self.anchors_per_cell, 1)
        for blk in (self.backbone, self.cls_head, self.box_head):
            blk.initialize(mx.init.Xavier())

    def params(self, gluon):
        p = gluon.parameter.ParameterDict()
        for blk in (self.backbone, self.cls_head, self.box_head):
            p.update(blk.collect_params())
        return p

    def forward(self, nd, x):
        feat = self.backbone(x)
        anchors = nd.contrib.MultiBoxPrior(
            feat, sizes=(0.3, 0.6), ratios=(1.0, 1.7))
        n_anchor = anchors.shape[1]
        b = x.shape[0]
        cls_pred = self.cls_head(feat).transpose((0, 2, 3, 1)).reshape(
            (b, n_anchor, self.num_classes + 1))
        box_pred = self.box_head(feat).transpose((0, 2, 3, 1)).reshape(
            (b, n_anchor * 4))
        return anchors, cls_pred, box_pred


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=48)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--steps-per-epoch", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    model = ToySSD(mx, gluon, num_classes=1)
    trainer = gluon.Trainer(model.params(gluon), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss(rho=1.0)   # smooth-l1 on masked offsets

    first = last = None
    for epoch in range(args.epochs):
        tot = 0.0
        for _ in range(args.steps_per_epoch):
            xb, lb = make_batch(rng, args.batch_size, args.image_size)
            x = nd.array(xb)
            label = nd.array(lb)
            with autograd.record():
                anchors, cls_pred, box_pred = model.forward(nd, x)
                box_t, box_m, cls_t = nd.contrib.MultiBoxTarget(
                    anchors, label, cls_pred.transpose((0, 2, 1)),
                    overlap_threshold=0.5, negative_mining_ratio=3.0,
                    minimum_negative_samples=0, variances=(0.1, 0.1,
                                                           0.2, 0.2))
                lc = cls_loss(cls_pred, cls_t)
                lbx = box_loss(box_pred * box_m, box_t * box_m)
                loss = lc + lbx
            loss.backward()
            trainer.step(args.batch_size)
            tot += float(loss.mean().asnumpy())
        avg = tot / args.steps_per_epoch
        if first is None:
            first = avg
        last = avg
        print(f"epoch {epoch}: loss {avg:.4f}")

    assert last < first, (first, last)

    # decode detections from the trained model on a fresh batch
    xb, lb = make_batch(rng, 1, args.image_size)
    anchors, cls_pred, box_pred = model.forward(nd, nd.array(xb))
    probs = nd.softmax(cls_pred, axis=-1).transpose((0, 2, 1))
    dets = nd.contrib.MultiBoxDetection(probs, box_pred, anchors,
                                        nms_threshold=0.45)
    rows = dets.asnumpy()[0]
    kept = rows[rows[:, 0] >= 0]
    top = kept[np.argmax(kept[:, 1])] if len(kept) else rows[0]
    print("ground truth:", lb[0, 0])
    print("top detection [cls conf x1 y1 x2 y2]:", np.round(top, 3))
    print("SSD_TRAIN_OK", first, "->", last)


if __name__ == "__main__":
    main()
