#!/usr/bin/env python
"""Deep autoencoder with layer-wise pretraining (reference
example/autoencoder/autoencoder.py).

The reference's AutoEncoderModel pretrains each encoder/decoder pair
greedily, then finetunes end to end. Same protocol here on a synthetic
manifold dataset (points on a noisy 2-D surface embedded in 32-D), so
the reconstruction loss and the benefit of finetuning are visible in
seconds.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_data(rng, n=512, dim=32):
    t = rng.rand(n, 2).astype(np.float32) * 2 - 1
    basis = rng.randn(6, dim).astype(np.float32)
    feats = np.stack([t[:, 0], t[:, 1], t[:, 0] * t[:, 1],
                      np.sin(3 * t[:, 0]), t[:, 0] ** 2, t[:, 1] ** 2], 1)
    return feats @ basis + rng.randn(n, dim).astype(np.float32) * 0.05


class Pair:
    """One encoder/decoder layer pair."""

    def __init__(self, gluon, mx, n_in, n_hidden, act):
        self.enc = gluon.nn.Dense(n_hidden, activation=act,
                                  in_units=n_in)
        self.dec = gluon.nn.Dense(n_in, activation=None,
                                  in_units=n_hidden)
        self.enc.initialize(mx.init.Xavier())
        self.dec.initialize(mx.init.Xavier())

    def params(self, gluon):
        p = gluon.parameter.ParameterDict()
        p.update(self.enc.collect_params())
        p.update(self.dec.collect_params())
        return p


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dims", type=int, nargs="+", default=[32, 16, 4])
    ap.add_argument("--pretrain-epochs", type=int, default=15)
    ap.add_argument("--finetune-epochs", type=int, default=25)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    X = make_data(rng)
    l2 = gluon.loss.L2Loss()

    def epochs(params, fwd, n_epochs, data):
        trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})
        hist = []
        for _ in range(n_epochs):
            perm = rng.permutation(len(data))
            tot, nb = 0.0, 0
            for i in range(0, len(data), args.batch_size):
                xb = nd.array(data[perm[i:i + args.batch_size]])
                with autograd.record():
                    loss = l2(fwd(xb), xb)
                loss.backward()
                trainer.step(xb.shape[0])
                tot += float(loss.mean().asnumpy())
                nb += 1
            hist.append(tot / nb)
        return hist

    # 1) greedy layer-wise pretraining (reference AutoEncoderModel.layerwise_pretrain)
    pairs = []
    cur = X
    for n_in, n_hid in zip(args.dims[:-1], args.dims[1:]):
        pair = Pair(gluon, mx, n_in, n_hid, "tanh")
        hist = epochs(pair.params(gluon),
                      lambda x, p=pair: p.dec(p.enc(x)),
                      args.pretrain_epochs, cur)
        print(f"pretrain {n_in}->{n_hid}: loss {hist[0]:.4f} -> "
              f"{hist[-1]:.4f}")
        cur = pair.enc(nd.array(cur)).asnumpy()
        pairs.append(pair)

    # 2) end-to-end finetune (reference .finetune)
    all_params = mx.gluon.parameter.ParameterDict()
    for p in pairs:
        all_params.update(p.params(mx.gluon))

    def full(x):
        for p in pairs:
            x = p.enc(x)
        for p in reversed(pairs):
            x = p.dec(x)
        return x

    hist = epochs(all_params, full, args.finetune_epochs, X)
    print(f"finetune: loss {hist[0]:.4f} -> {hist[-1]:.4f}")
    assert hist[-1] < hist[0], (hist[0], hist[-1])
    print("AUTOENCODER_OK", hist[0], hist[-1])


if __name__ == "__main__":
    main()
