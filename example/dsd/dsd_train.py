#!/usr/bin/env python
"""Dense-Sparse-Dense training (reference example/dsd/ — Han et al.:
train dense, PRUNE the smallest weights and retrain under the sparsity
mask, then re-densify and train again; the sparse detour acts as a
regularizer that often beats straight dense training).

All three phases run here on a synthetic classification task. The
sparse phase enforces a 50% magnitude mask by re-applying it after
every optimizer step (the reference's masked-update semantics), and the
script asserts (a) the mask really held during the sparse phase and
(b) the final dense accuracy at least matches the phase-1 accuracy.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 8
DIM = 48


def make_data(rng, glyphs, n):
    y = rng.randint(0, N_CLASSES, n)
    X = glyphs[y] + 0.4 * rng.randn(n, DIM).astype(np.float32)
    return X.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dense-epochs", type=int, default=4)
    ap.add_argument("--sparse-epochs", type=int, default=4)
    ap.add_argument("--redense-epochs", type=int, default=3)
    ap.add_argument("--sparsity", type=float, default=0.5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    np.random.seed(args.seed)    # Xavier init draws from global np.random
    glyphs = (rng.rand(N_CLASSES, DIM) > 0.5).astype(np.float32)
    Xtr, ytr = make_data(rng, glyphs, 1024)
    Xte, yte = make_data(rng, glyphs, 256)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(96, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(N_CLASSES))
    net.initialize(mx.init.Xavier())
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def weights():
        return [p for name, p in sorted(net.collect_params().items())
                if name.endswith("weight")]

    def train(epochs, masks=None):
        n = len(Xtr)
        for _ in range(epochs):
            perm = rng.permutation(n)
            for s in range(0, n - args.batch_size + 1, args.batch_size):
                idx = perm[s:s + args.batch_size]
                with autograd.record():
                    loss = sce(net(nd.array(Xtr[idx])),
                               nd.array(ytr[idx])).mean()
                loss.backward()
                trainer.step(1)
                if masks is not None:
                    # masked-update semantics: pruned weights stay 0
                    for p, m in zip(weights(), masks):
                        p.set_data(p.data() * m)

    def accuracy():
        return float((net(nd.array(Xte)).asnumpy().argmax(1) == yte).mean())

    # phase 1: dense
    train(args.dense_epochs)
    acc_dense = accuracy()
    print(f"phase 1 (dense) accuracy {acc_dense:.3f}")

    # prune: per-layer magnitude threshold at the target sparsity
    masks = []
    for p in weights():
        w = p.data().asnumpy()
        thr = np.quantile(np.abs(w), args.sparsity)
        masks.append(nd.array((np.abs(w) > thr).astype(np.float32)))
    # phase 2: sparse retrain under the mask
    for p, m in zip(weights(), masks):
        p.set_data(p.data() * m)
    train(args.sparse_epochs, masks=masks)
    zero_frac = np.mean([float((p.data().asnumpy() == 0).mean())
                         for p in weights()])
    acc_sparse = accuracy()
    print(f"phase 2 (sparse @ {args.sparsity:.0%}) accuracy "
          f"{acc_sparse:.3f}, zero fraction {zero_frac:.2f}")
    assert zero_frac >= args.sparsity * 0.9, zero_frac  # mask really held

    # phase 3: re-densify (drop the mask) and fine-tune
    train(args.redense_epochs)
    acc_final = accuracy()
    print(f"phase 3 (re-dense) accuracy {acc_final:.3f}")
    assert acc_final >= acc_dense - 0.02, (acc_dense, acc_final)
    assert acc_final > 0.9, acc_final
    print("DSD_OK")


if __name__ == "__main__":
    main()
