#!/usr/bin/env python
"""REINFORCE policy gradient on a synthetic control task (reference
example/reinforcement-learning/ — a2c/parallel_actor_critic).

Environment: a 1-D 'cursor' with position drifting randomly; actions
{left, stay, right}; reward = -|position| each step. The optimal policy
pushes the cursor toward 0, so the mean episode return rises as the
gluon policy network learns. One process, batched rollouts, returns
standardized — the minimal on-policy policy-gradient loop.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


class CursorEnv:
    def __init__(self, rng, n, horizon=20):
        self.rng = rng
        self.n = n
        self.horizon = horizon

    def rollout(self, policy_fn):
        pos = self.rng.uniform(-2, 2, self.n).astype(np.float32)
        obs_l, act_l, rew_l = [], [], []
        for _ in range(self.horizon):
            obs = np.stack([pos, np.sign(pos)], 1).astype(np.float32)
            probs = policy_fn(obs)           # (n, 3)
            u = self.rng.rand(self.n, 1)
            act = (probs.cumsum(1) < u).sum(1).clip(0, 2)
            pos = pos + (act - 1) * 0.5 \
                + self.rng.randn(self.n).astype(np.float32) * 0.1
            obs_l.append(obs)
            act_l.append(act)
            rew_l.append(-np.abs(pos))
        return (np.stack(obs_l, 1), np.stack(act_l, 1),
                np.stack(rew_l, 1).astype(np.float32))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--episodes", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--gamma", type=float, default=0.95)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    env = CursorEnv(rng, args.batch)

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="tanh"))
    net.add(gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def policy_fn(obs):
        return nd.softmax(net(nd.array(obs)), axis=-1).asnumpy()

    returns_hist = []
    for ep in range(args.episodes):
        obs, act, rew = env.rollout(policy_fn)
        # discounted returns-to-go, standardized (the reference's
        # parallel_actor_critic advantage normalization)
        ret = np.zeros_like(rew)
        acc = np.zeros(rew.shape[0], np.float32)
        for t in range(rew.shape[1] - 1, -1, -1):
            acc = rew[:, t] + args.gamma * acc
            ret[:, t] = acc
        adv = (ret - ret.mean()) / (ret.std() + 1e-6)

        b, h = act.shape
        with autograd.record():
            logits = net(nd.array(obs.reshape(b * h, -1)))
            logp = nd.log_softmax(logits, axis=-1)
            sel = nd.pick(logp, nd.array(act.reshape(-1)), axis=1)
            loss = -(sel * nd.array(adv.reshape(-1))).mean()
        loss.backward()
        trainer.step(1)
        returns_hist.append(float(ret[:, 0].mean()))
        if ep % 10 == 0:
            print(f"episode {ep}: mean return {returns_hist[-1]:.2f}")

    first = np.mean(returns_hist[:5])
    last = np.mean(returns_hist[-5:])
    print(f"mean return first5 {first:.2f} -> last5 {last:.2f}")
    assert last > first, (first, last)
    print("REINFORCE_OK", first, last)


if __name__ == "__main__":
    main()
