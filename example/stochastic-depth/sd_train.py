#!/usr/bin/env python
"""Stochastic depth training (reference example/stochastic-depth/
sd_cifar10.py — Huang et al.: residual blocks are randomly DROPPED
during training with a linearly-decaying survival probability and kept
(scaled by that probability) at inference, regularizing very deep
residual nets and shortening expected train-time depth).

A small residual conv net on synthetic glyph images: each block's
train-time forward flips a per-batch Bernoulli(p) gate — the block is
pure identity when dropped — and inference scales the residual by p
(the expected-depth formulation). The script checks the net learns AND
that inference is deterministic (two eval passes identical) while
train-time forwards genuinely vary across gate draws.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 8
IMG = 16


def make_data(rng, glyphs, n):
    y = rng.randint(0, N_CLASSES, n)
    X = glyphs[y] + 0.3 * rng.randn(n, 1, IMG, IMG).astype(np.float32)
    return X.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--blocks", type=int, default=6)
    ap.add_argument("--p-last", type=float, default=0.5,
                    help="survival prob of the deepest block (linear decay)")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-acc", type=float, default=0.9)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    glyphs = (rng.rand(N_CLASSES, 1, IMG, IMG) > 0.5).astype(np.float32)
    Xtr, ytr = make_data(rng, glyphs, 1024)
    Xte, yte = make_data(rng, glyphs, 256)

    # linearly decaying survival probabilities (reference sd_module.py);
    # a single block just gets p_last
    if args.blocks == 1:
        survival = [args.p_last]
    else:
        survival = [1.0 - (l / (args.blocks - 1)) * (1.0 - args.p_last)
                    for l in range(args.blocks)]

    # plain (non-hybrid) Blocks ON PURPOSE: the gate is Python-level
    # randomness, which hybridize() would trace ONCE and freeze into the
    # cached graph — stochastic depth must re-flip per batch, so these
    # stay eager (the reference's sd_module is likewise imperative)
    class ResBlock(gluon.nn.Block):
        def __init__(self, channels, p, **kw):
            super().__init__(**kw)
            self.p = p
            with self.name_scope():
                self.c1 = gluon.nn.Conv2D(channels, 3, padding=1,
                                          activation="relu")
                self.c2 = gluon.nn.Conv2D(channels, 3, padding=1)

        def forward(self, x):
            res = self.c2(self.c1(x))
            if autograd.is_training():
                gate = float(np.random.rand() < self.p)  # per-batch flip
                return x + gate * res
            return x + self.p * res          # inference: expected depth

    class SDNet(gluon.nn.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.stem = gluon.nn.Conv2D(16, 3, padding=1,
                                            activation="relu")
                self.blocks = gluon.nn.Sequential()
                for l in range(args.blocks):
                    self.blocks.add(ResBlock(16, survival[l]))
                self.pool = gluon.nn.MaxPool2D(2)
                self.flat = gluon.nn.Flatten()
                self.out = gluon.nn.Dense(N_CLASSES)

        def forward(self, x):
            h = self.blocks(self.stem(x))
            return self.out(self.flat(self.pool(h)))

    np.random.seed(args.seed)
    net = SDNet()
    net.initialize(mx.init.Xavier())
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    # train-time forwards must differ across gate draws (depth is
    # random). One pair of draws matches with prob ~prod(p^2+(1-p)^2)
    # ~ 8% at these settings, so probe several pairs — and fail BEFORE
    # spending the training budget if the gates are dead.
    xb = nd.array(Xtr[:8])
    with autograd.train_mode():      # mode flag only — no tape needed
        outs = [net(xb).asnumpy() for _ in range(8)]
    varies = any(not np.allclose(outs[0], o) for o in outs[1:])
    assert varies, "train-time depth never varied - gates are dead"

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            with autograd.record():
                loss = sce(net(nd.array(Xtr[idx])),
                           nd.array(ytr[idx])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} loss {tot / (n // args.batch_size):.4f}")

    # inference is deterministic (blocks scaled by survival, not sampled)
    e1 = net(nd.array(Xte)).asnumpy()
    e2 = net(nd.array(Xte)).asnumpy()
    assert np.array_equal(e1, e2), "inference must be deterministic"
    acc = float((e1.argmax(1) == yte).mean())
    print(f"accuracy {acc:.3f} (train-time depth varied: {varies})")
    assert acc >= args.min_acc, acc
    print("STOCHASTIC_DEPTH_OK")


if __name__ == "__main__":
    main()
