#!/usr/bin/env python
"""Multi-task learning: one trunk, two supervised heads (reference
example/multi-task/example_multi_task.py — MNIST digit class + a second
derived task trained jointly from a shared convolutional trunk).

The synthetic 'digits' are glyph images (fixed random patterns + noise);
head 1 classifies the digit, head 2 its parity. A single backward pass
propagates the SUM of both losses through the shared trunk — the gradient
interference/synergy pattern multi-task training is about. Both
validation accuracies must beat chance by a wide margin.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 10
IMG = 16


def make_data(rng, glyphs, n):
    y = rng.randint(0, N_CLASSES, n)
    X = glyphs[y] + 0.3 * rng.randn(n, 1, IMG, IMG).astype(np.float32)
    return X.astype(np.float32), y.astype(np.float32), \
        (y % 2).astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-acc", type=float, default=0.85)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    glyphs = (rng.rand(N_CLASSES, 1, IMG, IMG) > 0.5).astype(np.float32)
    Xtr, ytr, ptr = make_data(rng, glyphs, 1024)
    Xte, yte, pte = make_data(rng, glyphs, 256)

    class MultiTaskNet(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.trunk = gluon.nn.HybridSequential()
                self.trunk.add(
                    gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                    gluon.nn.MaxPool2D(2),
                    gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
                    gluon.nn.MaxPool2D(2),
                    gluon.nn.Flatten(),
                    gluon.nn.Dense(64, activation="relu"))
                self.head_digit = gluon.nn.Dense(N_CLASSES)
                self.head_parity = gluon.nn.Dense(2)

        def hybrid_forward(self, F, x):
            h = self.trunk(x)
            return self.head_digit(h), self.head_parity(h)

    net = MultiTaskNet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            x = nd.array(Xtr[idx])
            yd, yp = nd.array(ytr[idx]), nd.array(ptr[idx])
            with autograd.record():
                od, op = net(x)
                loss = sce(od, yd).mean() + sce(op, yp).mean()
            loss.backward()          # ONE backward through the shared trunk
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} joint loss {tot / (n // args.batch_size):.4f}")

    od, op = net(nd.array(Xte))
    acc_d = float((od.asnumpy().argmax(1) == yte).mean())
    acc_p = float((op.asnumpy().argmax(1) == pte).mean())
    print(f"digit accuracy {acc_d:.3f}, parity accuracy {acc_p:.3f}")
    assert acc_d > args.min_acc and acc_p > args.min_acc, (acc_d, acc_p)
    print("MULTITASK_OK")


if __name__ == "__main__":
    main()
