#!/usr/bin/env python
"""Bernoulli restricted Boltzmann machine trained with contrastive
divergence (reference example/restricted-boltzmann-machine/
binary_rbm_gibbs_sampling.py — CD-k on binarized MNIST).

CD-1 on binarized glyph data, written directly against the nd API (the
update is not a gradient of a differentiable loss — it is the positive
minus negative phase statistics, so no autograd involved):

    dW ~ <v h>_data - <v h>_recon

Progress is measured two ways, like the reference: one-step
reconstruction error falls, and free energy of DATA drops relative to
free energy of RANDOM noise (the model assigns its probability mass to
the data manifold).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_VIS = 64
N_HID = 32


def make_data(rng, glyphs, n):
    y = rng.randint(0, len(glyphs), n)
    probs = np.clip(glyphs[y] * 0.9 + 0.05, 0, 1)
    return (rng.rand(n, N_VIS) < probs).astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    glyphs = (rng.rand(8, N_VIS) > 0.5).astype(np.float32)
    Xtr = make_data(rng, glyphs, 1024)

    W = nd.array(0.01 * rng.randn(N_VIS, N_HID).astype(np.float32))
    bv = nd.zeros((N_VIS,))
    bh = nd.zeros((N_HID,))

    sigmoid = nd.sigmoid          # stable framework op

    def sample(p):
        return (nd.random.uniform(shape=p.shape) < p).astype("float32")

    def free_energy(v):
        """F(v) = -v.bv - sum log(1 + exp(v W + bh)) (reference
        binary_rbm.py free energy)."""
        pre = nd.dot(v, W) + bh
        # overflow-stable softplus via the framework's softrelu
        softplus = nd.Activation(pre, act_type="softrelu")
        return -nd.dot(v, bv) - nd.sum(softplus, axis=1)

    def cd1(v0):
        ph0 = sigmoid(nd.dot(v0, W) + bh)        # positive phase
        h0 = sample(ph0)
        pv1 = sigmoid(nd.dot(h0, W, transpose_b=True) + bv)
        v1 = sample(pv1)
        ph1 = sigmoid(nd.dot(v1, W) + bh)        # negative phase
        B = v0.shape[0]
        dW = (nd.dot(v0, ph0, transpose_a=True)
              - nd.dot(v1, ph1, transpose_a=True)) / B
        dbv = nd.mean(v0 - v1, axis=0)
        dbh = nd.mean(ph0 - ph1, axis=0)
        err = float(nd.mean((v0 - pv1) ** 2).asnumpy())
        return dW, dbv, dbh, err

    n = len(Xtr)
    first_err = last_err = None
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot, nb = 0.0, 0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            v0 = nd.array(Xtr[perm[s:s + args.batch_size]])
            dW, dbv, dbh, err = cd1(v0)
            W = W + args.lr * dW
            bv = bv + args.lr * dbv
            bh = bh + args.lr * dbh
            tot += err; nb += 1
        avg = tot / nb
        first_err = first_err if first_err is not None else avg
        last_err = avg
        if epoch % 3 == 0:
            print(f"epoch {epoch} recon err {avg:.4f}")

    data_fe = float(nd.mean(free_energy(nd.array(Xtr[:256]))).asnumpy())
    noise = (rng.rand(256, N_VIS) > 0.5).astype(np.float32)
    noise_fe = float(nd.mean(free_energy(nd.array(noise))).asnumpy())
    print(f"recon err {first_err:.4f} -> {last_err:.4f}; "
          f"free energy data {data_fe:.1f} vs noise {noise_fe:.1f}")
    assert last_err < first_err * 0.7, (first_err, last_err)
    assert data_fe < noise_fe - 5.0, (data_fe, noise_fe)
    print("RBM_OK")


if __name__ == "__main__":
    main()
