#!/usr/bin/env python
"""MLP with an SVM head instead of softmax (reference
example/svm_mnist/svm_mnist.py — SVMOutput trains a one-vs-all hinge
loss; the notebook's point is that swapping SoftmaxOutput for SVMOutput
is a one-line change).

Trained on synthetic glyph digits with both SVM variants (L2 hinge, and
--use-linear for L1) via the Module/fit path the reference uses, then
scored by argmax over the margins.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 10
DIM = 64


def make_data(rng, glyphs, n):
    y = rng.randint(0, N_CLASSES, n)
    X = glyphs[y] + 0.3 * rng.randn(n, DIM).astype(np.float32)
    return X.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--use-linear", action="store_true",
                    help="L1 hinge (reference L1_SVM) instead of L2")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-acc", type=float, default=0.9)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx

    rng = np.random.RandomState(args.seed)
    glyphs = (rng.rand(N_CLASSES, DIM) > 0.5).astype(np.float32)
    Xtr, ytr = make_data(rng, glyphs, 1024)
    Xte, yte = make_data(rng, glyphs, 256)

    # the reference's exact symbol recipe: fc -> relu -> fc -> SVMOutput
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=N_CLASSES, name="fc2")
    net = mx.sym.SVMOutput(net, mx.sym.Variable("svm_label"),
                           margin=1.0, regularization_coefficient=1.0,
                           use_linear=args.use_linear, name="svm")

    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("svm_label",))
    train_iter = mx.io.NDArrayIter(data=Xtr, label=ytr,
                                   batch_size=args.batch_size, shuffle=True,
                                   label_name="svm_label")
    val_iter = mx.io.NDArrayIter(data=Xte, label=yte,
                                 batch_size=args.batch_size,
                                 label_name="svm_label")
    mod.fit(train_iter, eval_data=val_iter,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            eval_metric="acc", num_epoch=args.epochs)
    score = mod.score(val_iter, "acc")
    acc = dict(score)["accuracy"]
    print(f"SVM-head validation accuracy: {acc:.3f} "
          f"({'L1' if args.use_linear else 'L2'} hinge)")
    assert acc >= args.min_acc, acc
    print("SVM_MNIST_OK")


if __name__ == "__main__":
    main()
