#!/usr/bin/env python
"""Fully convolutional semantic segmentation (reference
example/fcn-xs/fcn_xs.py + symbol_fcnxs.py — FCN-32s/16s/8s: a conv
backbone whose stride-accumulated features are upsampled back to pixel
resolution with Deconvolution and trained with per-pixel softmax).

Synthetic scenes contain axis-aligned rectangles of two object classes on
a noisy background; the net downsamples 4x through the trunk, then a
Conv2DTranspose chain (the fcn-xs 'upscore' layers) restores resolution,
with a skip connection fusing the stride-2 feature map into the upsampled
deep features — the FCN-16s trick. Scored by mean intersection-over-union,
the segmentation literature's standard metric.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 3      # background + 2 object classes
IMG = 32


def make_data(rng, n):
    X = 0.2 * rng.randn(n, 3, IMG, IMG).astype(np.float32)
    Y = np.zeros((n, IMG, IMG), np.float32)
    for i in range(n):
        for cls in (1, 2):
            h, w = rng.randint(6, 14, 2)
            r, c = rng.randint(0, IMG - h), rng.randint(0, IMG - w)
            # each class paints a distinct channel signature
            X[i, cls - 1, r:r + h, c:c + w] += 1.0
            X[i, 2, r:r + h, c:c + w] += 0.5 if cls == 1 else -0.5
            Y[i, r:r + h, c:c + w] = cls
    return X, Y


def mean_iou(pred, label):
    ious = []
    for c in range(N_CLASSES):
        inter = np.logical_and(pred == c, label == c).sum()
        union = np.logical_or(pred == c, label == c).sum()
        if union:
            ious.append(inter / union)
    return float(np.mean(ious))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-iou", type=float, default=0.6)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    Xtr, Ytr = make_data(rng, 512)
    Xte, Yte = make_data(rng, 128)

    class FCN(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.down1 = gluon.nn.HybridSequential()   # stride 2
                self.down1.add(
                    gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                    gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                                    activation="relu"))
                self.down2 = gluon.nn.HybridSequential()   # stride 4
                self.down2.add(
                    gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
                    gluon.nn.Conv2D(32, 3, strides=2, padding=1,
                                    activation="relu"))
                # upscore layers (reference symbol_fcnxs.py Deconvolution)
                self.up1 = gluon.nn.Conv2DTranspose(16, 4, strides=2,
                                                    padding=1)
                self.up2 = gluon.nn.Conv2DTranspose(16, 4, strides=2,
                                                    padding=1)
                self.skip = gluon.nn.Conv2D(16, 1)         # FCN-16s fuse
                self.score = gluon.nn.Conv2D(N_CLASSES, 1)

        def hybrid_forward(self, F, x):
            f1 = self.down1(x)                 # (B,16,H/2,W/2)
            f2 = self.down2(f1)                # (B,32,H/4,W/4)
            u1 = F.relu(self.up1(f2) + self.skip(f1))
            u2 = F.relu(self.up2(u1))          # (B,16,H,W)
            return self.score(u2)              # (B,C,H,W)

    net = FCN()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    sce = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            with autograd.record():
                loss = sce(net(x), y).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} pixel loss {tot / (n // args.batch_size):.4f}")

    pred = net(nd.array(Xte)).asnumpy().argmax(axis=1)
    iou = mean_iou(pred, Yte)
    print(f"mean IoU: {iou:.3f}")
    assert iou > args.min_iou, f"mean IoU {iou} < {args.min_iou}"
    print("FCN_XS_OK")


if __name__ == "__main__":
    main()
