#!/usr/bin/env python
"""PTB word-level language model with the fused LSTM (reference
example/rnn/bucketing/lstm_bucketing.py — BASELINE config 3).

Reads ptb.train.txt when --data-dir has it (space-separated tokens, one
sentence per line), else trains on a synthetic Markov-chain corpus so the
script runs anywhere. The model is gluon.rnn.LSTM (the fused lax.scan op,
ops/rnn_ops.py) + tied softmax over a hybridized forward.
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def load_corpus(args):
    path = os.path.join(args.data_dir, "ptb.train.txt")
    if os.path.exists(path):
        words = open(path).read().replace("\n", " <eos> ").split()
        vocab = {w: i for i, w in enumerate(sorted(set(words)))}
        data = np.asarray([vocab[w] for w in words], np.int32)
        return data, len(vocab)
    # synthetic: order-1 Markov chain with a sparse transition matrix, so
    # an LM can reach a clearly-sub-uniform perplexity
    V = args.vocab
    rs = np.random.RandomState(0)
    trans = rs.dirichlet(np.full(8, 0.5), size=V)
    nexts = np.stack([rs.choice(V, 8, replace=False) for _ in range(V)])
    seq = [0]
    for _ in range(args.num_tokens - 1):
        row = seq[-1]
        seq.append(int(nexts[row][rs.choice(8, p=trans[row])]))
    return np.asarray(seq, np.int32), V


def batchify(data, batch, seqlen):
    n = (len(data) - 1) // (batch * seqlen)
    x = data[:n * batch * seqlen].reshape(batch, n * seqlen)
    y = data[1:n * batch * seqlen + 1].reshape(batch, n * seqlen)
    for i in range(n):
        sl = slice(i * seqlen, (i + 1) * seqlen)
        yield x[:, sl], y[:, sl]


def main():
    p = argparse.ArgumentParser(description="PTB LSTM LM")
    p.add_argument("--data-dir", default="./ptb")
    p.add_argument("--vocab", type=int, default=200)
    p.add_argument("--num-tokens", type=int, default=30000)
    p.add_argument("--emsize", type=int, default=128)
    p.add_argument("--nhid", type=int, default=128)
    p.add_argument("--nlayers", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--bptt", type=int, default=35)
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=1.0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.gluon import nn, rnn

    data, V = load_corpus(args)
    logging.info("corpus: %d tokens, vocab %d", len(data), V)

    class RNNModel(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.embed = nn.Embedding(V, args.emsize)
            self.lstm = rnn.LSTM(args.nhid, num_layers=args.nlayers,
                                 layout="NTC")
            self.decoder = nn.Dense(V, flatten=False)

        def forward(self, x):
            h = self.embed(x)
            out = self.lstm(h)      # states=None -> fresh zero state
            return self.decoder(out)

    model = RNNModel()
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr, "clip_gradient": 5.0})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.num_epochs):
        total, count, tic = 0.0, 0, time.time()
        for x, y in batchify(data, args.batch_size, args.bptt):
            xb, yb = mx.nd.array(x), mx.nd.array(y.astype(np.float32))
            with autograd.record():
                out = model(xb)
                loss = loss_fn(out.reshape((-1, V)),
                               yb.reshape((-1,))).mean()
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy()) * x.size
            count += x.size
        ppl = np.exp(total / count)
        logging.info("epoch %d: perplexity %.2f (uniform=%d)  %.0f tok/s",
                     epoch, ppl, V, count / (time.time() - tic))


if __name__ == "__main__":
    main()
