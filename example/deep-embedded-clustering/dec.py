#!/usr/bin/env python
"""Deep Embedded Clustering (reference example/deep-embedded-clustering/
dec.py — Xie et al.: pretrain an autoencoder, then refine the encoder so
the latent space clusters, by minimizing KL(P || Q) between the soft
Student-t cluster assignments Q and a sharpened target distribution P).

Unsupervised end to end on synthetic multi-mode data: labels are used
ONLY for evaluation. The three DEC ingredients are all here — autoencoder
pretraining, Student-t similarity q_ij between embeddings and cluster
centers (centers initialized by a few k-means steps in latent space and
TRAINED by the KL loss alongside the encoder), and the self-sharpening
target p_ij = q^2/f normalized. Scored by cluster accuracy under the
best cluster-to-class matching (the DEC paper's metric).
"""
import argparse
import itertools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLUSTERS = 4
DIM = 32
LATENT = 5


def make_data(rng, modes, n):
    y = rng.randint(0, N_CLUSTERS, n)
    X = modes[y] + 0.30 * rng.randn(n, DIM).astype(np.float32)
    return X.astype(np.float32), y


def cluster_accuracy(assign, y):
    """Best accuracy over cluster->class permutations (DEC's metric)."""
    best = 0.0
    for perm in itertools.permutations(range(N_CLUSTERS)):
        mapped = np.asarray(perm)[assign]
        best = max(best, float((mapped == y).mean()))
    return best


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pretrain-epochs", type=int, default=8)
    ap.add_argument("--dec-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--center-lr", type=float, default=0.2,
                    help="SGD step for the cluster centers (the KL "
                         "gradient wrt one center is tiny; centers need "
                         "a far larger rate than the encoder)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-acc", type=float, default=0.9)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    np.random.seed(args.seed)
    modes = rng.randn(N_CLUSTERS, DIM).astype(np.float32) * 1.5
    X, y = make_data(rng, modes, 1024)

    class AE(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.enc = gluon.nn.HybridSequential()
                self.enc.add(gluon.nn.Dense(64, activation="relu"),
                             gluon.nn.Dense(LATENT))
                self.dec = gluon.nn.HybridSequential()
                self.dec.add(gluon.nn.Dense(64, activation="relu"),
                             gluon.nn.Dense(DIM))

        def hybrid_forward(self, F, x):
            return self.dec(self.enc(x))

    ae = AE()
    ae.initialize(mx.init.Xavier())
    l2 = gluon.loss.L2Loss()
    trainer = gluon.Trainer(ae.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(X)
    for epoch in range(args.pretrain_epochs):     # phase 1: reconstruction
        perm = rng.permutation(n)
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            xb = nd.array(X[perm[s:s + args.batch_size]])
            with autograd.record():
                loss = l2(ae(xb), xb).mean()
            loss.backward()
            trainer.step(1)

    z = ae.enc(nd.array(X)).asnumpy()
    # centers: k-means in the pretrained latent — DEC's OWN
    # prescription (the KL objective REFINES an initial partition; it
    # self-confirms rather than discovers, which is why the paper
    # mandates k-means init).
    centers = z[rng.choice(n, N_CLUSTERS, replace=False)].copy()
    for _ in range(10):
        d = ((z[:, None, :] - centers[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for k in range(N_CLUSTERS):
            if (a == k).any():
                centers[k] = z[a == k].mean(0)

    d = ((z[:, None, :] - centers[None]) ** 2).sum(-1)
    acc_init = cluster_accuracy(d.argmin(1), y)
    print(f"k-means-init cluster accuracy: {acc_init:.3f}")

    # phase 2: DEC refinement — centers become a trainable parameter and
    # ONLY the encoder trains (the decoder has no gradient in the KL
    # loss; keeping it in the trainer would re-apply its stale
    # pretraining gradient every step)
    trainer = gluon.Trainer(ae.enc.collect_params(), "adam",
                            {"learning_rate": args.lr})
    mu = nd.array(centers)
    mu.attach_grad()

    def soft_assign(zb):
        """Student-t similarity q_ij (alpha=1), the DEC kernel."""
        d2 = nd.sum((zb.reshape((-1, 1, LATENT)) -
                     mu.reshape((1, N_CLUSTERS, LATENT))) ** 2, axis=2)
        q = 1.0 / (1.0 + d2)
        return q / nd.sum(q, axis=1, keepdims=True)

    conf_init = None
    for epoch in range(args.dec_epochs):
        # target distribution recomputed per epoch from the FULL data
        q_all = soft_assign(ae.enc(nd.array(X))).asnumpy()
        if conf_init is None:
            conf_init = float(q_all.max(1).mean())
        f = q_all.sum(0)
        p_all = (q_all ** 2) / f
        p_all = p_all / p_all.sum(1, keepdims=True)
        perm = rng.permutation(n)
        tot, nb = 0.0, 0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            xb = nd.array(X[idx])
            pb = nd.array(p_all[idx])
            with autograd.record():
                qb = soft_assign(ae.enc(xb))
                kl = nd.sum(pb * (nd.log(pb + 1e-9) - nd.log(qb + 1e-9)),
                            axis=1).mean()
            kl.backward()
            trainer.step(1)                       # encoder
            mu = mu - args.center_lr * mu.grad    # centers (SGD)
            mu.attach_grad()
            tot += float(kl.asnumpy()); nb += 1
        a_now = cluster_accuracy(
            soft_assign(ae.enc(nd.array(X))).asnumpy().argmax(1), y)
        print(f"dec epoch {epoch} KL {tot / nb:.4f} acc {a_now:.3f}")

    q_final = soft_assign(ae.enc(nd.array(X))).asnumpy()
    acc = cluster_accuracy(q_final.argmax(1), y)
    conf_final = float(q_final.max(1).mean())
    print(f"unsupervised cluster accuracy: {acc:.3f} "
          f"(k-means init was {acc_init:.3f}); "
          f"assignment confidence {conf_init:.3f} -> {conf_final:.3f}")
    assert acc >= args.min_acc, acc
    assert acc >= acc_init, (acc_init, acc)   # refinement never degrades
    # and the DEC objective's OBSERVABLE effect — assignments sharpen
    # toward the target distribution — must actually have happened
    # (this is what KL(P||Q) optimizes; accuracy alone could pass with
    # the whole phase silently broken when k-means is already perfect).
    # Relative headroom, since confidence may start near its ceiling:
    # the residual uncertainty (1 - mean max q) must shrink >= 5%.
    assert (1 - conf_final) < (1 - conf_init) * 0.95, \
        (conf_init, conf_final)
    print("DEC_OK")


if __name__ == "__main__":
    main()
