#!/usr/bin/env python
"""Gluon word-level language model (reference example/gluon/word_language_model):
Embedding -> LSTM -> tied-ish Dense decoder trained with truncated BPTT.

Corpus: --data a tokenized text file, else a synthetic Zipf stream with
learnable bigram structure so perplexity visibly improves anywhere.
"""
import argparse
import logging
import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def synthetic_corpus(vocab, n, rng):
    """Markov chain: token t+1 = (t*3 + noise) % vocab — learnable."""
    toks = np.empty(n, np.int32)
    toks[0] = rng.randint(vocab)
    for i in range(1, n):
        toks[i] = (toks[i - 1] * 3 + rng.randint(3)) % vocab
    return toks


def batchify(toks, batch_size, seq_len):
    nbatch = (len(toks) - 1) // (batch_size * seq_len)
    usable = nbatch * batch_size * seq_len
    data = toks[:usable].reshape(batch_size, -1)
    target = toks[1:usable + 1].reshape(batch_size, -1)
    for i in range(0, data.shape[1], seq_len):
        yield data[:, i:i + seq_len], target[:, i:i + seq_len]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None, help="tokenized text file")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--emsize", type=int, default=64)
    ap.add_argument("--nhid", type=int, default=128)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--tokens", type=int, default=20000)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(0)
    if args.data and os.path.exists(args.data):
        words = open(args.data).read().split()
        uniq = {w: i for i, w in enumerate(dict.fromkeys(words))}
        toks = np.array([uniq[w] for w in words], np.int32)
        args.vocab = len(uniq)
    else:
        toks = synthetic_corpus(args.vocab, args.tokens, rng)

    class RNNModel(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.embed = gluon.nn.Embedding(args.vocab, args.emsize)
            self.rnn = gluon.rnn.LSTM(args.nhid, layout="NTC")
            self.decoder = gluon.nn.Dense(args.vocab, flatten=False)

        def hybrid_forward(self, F, x, state=None):
            h = self.embed(x)
            if state is None:
                out = self.rnn(h)
                return self.decoder(out)
            out, state = self.rnn(h, state)
            return self.decoder(out), state

    model = RNNModel()
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    first_ppl = last_ppl = None
    for epoch in range(args.epochs):
        total, count = 0.0, 0
        for data, target in batchify(toks, args.batch_size, args.seq_len):
            x = nd.array(data.astype(np.float32))
            y = nd.array(target.astype(np.float32))
            with autograd.record():
                out = model(x)
                loss = loss_fn(out.reshape((-1, args.vocab)),
                               y.reshape((-1,)))
            loss.backward()
            trainer.step(x.shape[0] * args.seq_len)
            total += float(loss.mean().asnumpy()) * x.shape[0]
            count += x.shape[0]
        ppl = math.exp(min(20.0, total / max(count, 1)))
        if first_ppl is None:
            first_ppl = ppl
        last_ppl = ppl
        logging.info("epoch %d: perplexity %.2f", epoch, ppl)
    print(f"perplexity: first {first_ppl:.2f} last {last_ppl:.2f}")


if __name__ == "__main__":
    main()
