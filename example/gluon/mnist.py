#!/usr/bin/env python
"""Gluon MNIST: the imperative training loop (reference
example/gluon/mnist/mnist.py) — net + Trainer + autograd, no Module.

Runs on real MNIST idx files when --data-dir has them, else a synthetic
digit stream (same generator as the Module-API example) so the script
runs anywhere.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def synthetic_digits(n, rng):
    """Linearly-separable 28x28 'digits': class k lights block k."""
    x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.25
    y = rng.randint(0, 10, n)
    for i, k in enumerate(y):
        r, c = divmod(int(k), 4)
        x[i, 0, 7 * r:7 * r + 7, 7 * c:7 * c + 7] += 0.75
    return x, y.astype(np.float32)


def build_net(gluon, hidden):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(hidden, activation="relu"))
    net.add(gluon.nn.Dense(hidden // 2, activation="relu"))
    net.add(gluon.nn.Dense(10))
    return net


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=100)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--num-examples", type=int, default=2000)
    ap.add_argument("--hybridize", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(42)
    xs, ys = synthetic_digits(args.num_examples, rng)
    xv, yv = synthetic_digits(max(200, args.num_examples // 5), rng)
    train_data = gluon.data.DataLoader(
        gluon.data.ArrayDataset(nd.array(xs), nd.array(ys)),
        batch_size=args.batch_size, shuffle=True)
    val_data = gluon.data.DataLoader(
        gluon.data.ArrayDataset(nd.array(xv), nd.array(yv)),
        batch_size=args.batch_size)

    net = build_net(gluon, args.hidden)
    net.initialize(mx.init.Xavier())
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        for x, y in train_data:
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
        name, train_acc = metric.get()
        metric.reset()
        for x, y in val_data:
            metric.update([y], [net(x)])
        _, val_acc = metric.get()
        logging.info("epoch %d: train-%s=%.4f val-%s=%.4f",
                     epoch, name, train_acc, name, val_acc)
    print(f"final validation accuracy: {val_acc:.4f}")


if __name__ == "__main__":
    main()
