#!/usr/bin/env python
"""SSD-style detection inference (reference example/ssd): a toy backbone
plus the REAL detection op stack — multibox_prior anchors, class/box
heads, MultiBoxDetection decode with per-class NMS.

Demonstrates the contrib detection family end-to-end: anchors are laid
over the feature map, heads predict per-anchor class scores + box
offsets, and MultiBoxDetection turns them into [cls, score, x1 y1 x2 y2]
rows. Weights are random (inference plumbing demo, not a trained model);
--seed-boxes plants synthetic 'objects' by biasing the heads toward two
known anchors so the decoded output provably tracks the predictions.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--num-classes", type=int, default=3)
    ap.add_argument("--nms-threshold", type=float, default=0.45)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd

    S = args.image_size
    C = args.num_classes

    backbone = gluon.nn.HybridSequential()
    for ch in (16, 32):
        backbone.add(gluon.nn.Conv2D(ch, 3, padding=1, strides=2,
                                     activation="relu"))
    backbone.initialize(mx.init.Xavier())

    x = nd.array(np.random.RandomState(0).rand(1, 3, S, S)
                 .astype(np.float32))
    feat = backbone(x)                       # (1, 32, S/4, S/4)
    fh, fw = feat.shape[2], feat.shape[3]

    # anchors over the feature map (2 sizes x 2 ratios -> 3 per cell)
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.2, 0.4),
                                       ratios=(1.0, 2.0))
    num_anchors = anchors.shape[1]

    # per-anchor heads (1x1 convs), reshaped to the detection layout
    cls_head = gluon.nn.Conv2D((C + 1) * 3, 1)
    box_head = gluon.nn.Conv2D(4 * 3, 1)
    cls_head.initialize(mx.init.Xavier())
    box_head.initialize(mx.init.Xavier())

    cls_pred = cls_head(feat).transpose((0, 2, 3, 1)).reshape(
        (1, num_anchors, C + 1))
    # plant two confident 'detections' so the decode provably works
    cp = np.array(cls_pred.asnumpy())
    cp[:, :, 0] = 4.0                        # background everywhere...
    cp[0, 7, 1] = 9.0                        # ...except anchor 7 (class 0)
    cp[0, num_anchors // 2, 2] = 9.0         # and a middle anchor (class 1)
    cls_prob = nd.softmax(nd.array(cp), axis=-1).transpose((0, 2, 1))
    loc_pred = box_head(feat).transpose((0, 2, 3, 1)).reshape(
        (1, num_anchors * 4)) * 0.01

    out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       nms_threshold=args.nms_threshold,
                                       force_suppress=False)
    dets = out.asnumpy()[0]
    kept = dets[dets[:, 0] >= 0]
    kept = kept[np.argsort(-kept[:, 1])]
    print(f"anchors: {num_anchors} over {fh}x{fw} feature map")
    print("top detections [class score x1 y1 x2 y2]:")
    for row in kept[:5]:
        print("  " + " ".join(f"{v:7.3f}" for v in row))
    assert len(kept) >= 2, "planted detections were suppressed"
    assert {int(kept[0, 0]), int(kept[1, 0])} == {0, 1}, \
        "decoded classes do not match the planted objects"
    print(f"detections kept: {len(kept)} (2 planted objects recovered)")


if __name__ == "__main__":
    main()
