#!/usr/bin/env python
"""DCGAN on synthetic data (reference example/gan/dcgan.py).

Generator and discriminator are gluon HybridBlocks trained adversarially
with the standard non-saturating GAN losses. The 'dataset' is a family
of 16x16 images with planted structure (a bright centered disc of random
radius), so D/G dynamics are observable in seconds: D accuracy starts
high, G learns to place mass in the disc region, and the generated
images' center-vs-border contrast rises toward the real data's.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def real_batch(rng, n):
    yy, xx = np.mgrid[0:16, 0:16]
    imgs = np.zeros((n, 1, 16, 16), np.float32)
    for i in range(n):
        r = rng.uniform(3, 6)
        mask = (yy - 7.5) ** 2 + (xx - 7.5) ** 2 <= r * r
        imgs[i, 0][mask] = 1.0
    imgs += rng.randn(n, 1, 16, 16).astype(np.float32) * 0.05
    return imgs * 2 - 1          # [-1, 1] like the reference's tanh range


def build_nets(mx, gluon, latent):
    G = gluon.nn.HybridSequential()
    # latent -> 4x4 -> 8x8 -> 16x16 (reference netG's Conv2DTranspose stack)
    G.add(gluon.nn.Dense(64 * 4 * 4))
    G.add(gluon.nn.Activation("relu"))
    G.add(gluon.nn.HybridLambda(lambda F, x: F.reshape(x, shape=(-1, 64, 4, 4))))
    G.add(gluon.nn.Conv2DTranspose(32, 4, strides=2, padding=1))
    G.add(gluon.nn.Activation("relu"))
    G.add(gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                   activation="tanh"))
    D = gluon.nn.HybridSequential()
    D.add(gluon.nn.Conv2D(32, 4, strides=2, padding=1))
    D.add(gluon.nn.LeakyReLU(0.2))
    D.add(gluon.nn.Conv2D(64, 4, strides=2, padding=1))
    D.add(gluon.nn.LeakyReLU(0.2))
    D.add(gluon.nn.Flatten())
    D.add(gluon.nn.Dense(1))
    G.initialize(mx.init.Normal(0.02))
    D.initialize(mx.init.Normal(0.02))
    G.hybridize()
    D.hybridize()
    return G, D


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--latent", type=int, default=16)
    ap.add_argument("--steps", type=int, default=80)
    ap.add_argument("--lr", type=float, default=5e-4)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    G, D = build_nets(mx, gluon, args.latent)
    trainer_g = gluon.Trainer(G.collect_params(), "adam",
                              {"learning_rate": args.lr, "beta1": 0.5})
    trainer_d = gluon.Trainer(D.collect_params(), "adam",
                              {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    b = args.batch_size
    ones = nd.ones((b,))
    zeros = nd.zeros((b,))
    d_losses, g_losses = [], []
    for step in range(args.steps):
        real = nd.array(real_batch(rng, b))
        z = nd.array(rng.randn(b, args.latent).astype(np.float32))
        # D step: real -> 1, fake -> 0
        with autograd.record():
            fake = G(z)
            l_d = loss_fn(D(real), ones) + \
                loss_fn(D(fake.detach()), zeros)
        l_d.backward()
        trainer_d.step(b)
        # G step: non-saturating loss, fake -> 1
        with autograd.record():
            l_g = loss_fn(D(G(z)), ones)
        l_g.backward()
        trainer_g.step(b)
        d_losses.append(float(l_d.mean().asnumpy()))
        g_losses.append(float(l_g.mean().asnumpy()))
        if step % 20 == 0:
            print(f"step {step}: D {d_losses[-1]:.3f} G {g_losses[-1]:.3f}")

    # diagnostic: the data's center-vs-border contrast in generated
    # images (rises toward ~1.8 with more --steps)
    z = nd.array(rng.randn(64, args.latent).astype(np.float32))
    imgs = G(z).asnumpy()
    contrast = imgs[:, :, 6:10, 6:10].mean() - np.concatenate(
        [imgs[:, :, :2, :].ravel(), imgs[:, :, -2:, :].ravel()]).mean()
    print(f"generated center-border contrast: {contrast:.3f} "
          f"(real data ~1.8; rises with --steps)")
    # gates kept test-time robust: the adversarial game must be LIVE
    # (D learned to separate, both losses finite and neither collapsed);
    # full visual convergence needs more --steps than a smoke run
    assert d_losses[0] > d_losses[-1], (d_losses[0], d_losses[-1])
    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()
    assert g_losses[-1] > 0.05, "D collapsed (G loss ~0)"
    print("DCGAN_OK", d_losses[-1], g_losses[-1])


if __name__ == "__main__":
    main()
