#!/usr/bin/env python
"""Two-stage object detection, Faster R-CNN style (reference
example/rcnn/train_end2end.py — RPN + region classifier trained
jointly over a shared backbone; symbol_resnet.py wires Proposal +
ROIPooling between the stages).

Scaled to a self-contained synthetic task: each image plants ONE
axis-aligned box of one of two object classes (distinct channel
signatures). The pipeline is the real one —

  backbone conv features (stride 4)
  -> RPN head: per-anchor objectness + bbox deltas
     (anchor targets = IoU-matched on host, like rpn/anchor_target)
  -> _contrib_Proposal: decode deltas + NMS -> region proposals
  -> ROIAlign on the shared features
  -> region head: classify each proposal {bg, class1, class2}

— and the end metric is detection accuracy: does the top-scoring
proposal land on (IoU>=0.5) the planted box with the right class?
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

IMG = 32
STRIDE = 4
FEAT = IMG // STRIDE          # 8x8 feature map
ANCHOR_SCALES = (2, 3)        # anchor sides (in feature-stride units)
N_ANCHOR = len(ANCHOR_SCALES)
N_CLASSES = 3                 # background + 2 object classes


def anchors():
    """(FEAT*FEAT*N_ANCHOR, 4) anchor boxes in image pixels."""
    out = []
    for fy in range(FEAT):
        for fx in range(FEAT):
            cx, cy = (fx + 0.5) * STRIDE, (fy + 0.5) * STRIDE
            for s in ANCHOR_SCALES:
                half = s * STRIDE / 2
                out.append([cx - half, cy - half, cx + half, cy + half])
    return np.array(out, np.float32)


def iou(a, b):
    x1 = np.maximum(a[:, 0], b[0]); y1 = np.maximum(a[:, 1], b[1])
    x2 = np.minimum(a[:, 2], b[2]); y2 = np.minimum(a[:, 3], b[3])
    inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[2] - b[0]) * (b[3] - b[1])
    return inter / (area_a + area_b - inter + 1e-9)


def make_data(rng, n):
    X = 0.1 * rng.randn(n, 3, IMG, IMG).astype(np.float32)
    boxes = np.zeros((n, 4), np.float32)
    labels = np.zeros((n,), np.int64)
    for i in range(n):
        side = rng.randint(8, 17)
        x1 = rng.randint(0, IMG - side); y1 = rng.randint(0, IMG - side)
        cls = rng.randint(1, N_CLASSES)
        X[i, cls - 1, y1:y1 + side, x1:x1 + side] += 1.0
        boxes[i] = [x1, y1, x1 + side, y1 + side]
        labels[i] = cls
    return X, boxes, labels


def rpn_targets(anc, box):
    """Per-anchor (objectness in {-1,0,1}, bbox deltas) — the reference's
    rpn/anchor_target assignment: positive above 0.5 IoU (or argmax),
    negative below 0.2, rest ignored."""
    ious = iou(anc, box)
    obj = -np.ones(len(anc), np.float32)
    obj[ious < 0.2] = 0.0
    pos = ious >= 0.5
    pos[np.argmax(ious)] = True
    obj[pos] = 1.0
    # deltas in the standard (dx, dy, dw, dh) parameterization
    aw = anc[:, 2] - anc[:, 0]; ah = anc[:, 3] - anc[:, 1]
    acx = anc[:, 0] + aw / 2;   acy = anc[:, 1] + ah / 2
    bw = box[2] - box[0]; bh = box[3] - box[1]
    bcx = box[0] + bw / 2; bcy = box[1] + bh / 2
    deltas = np.stack([(bcx - acx) / aw, (bcy - acy) / ah,
                       np.log(bw / aw), np.log(bh / ah)], 1).astype(np.float32)
    return obj, deltas


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-acc", type=float, default=0.6)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    anc = anchors()
    Xtr, Btr, Ltr = make_data(rng, 384)
    Xte, Bte, Lte = make_data(rng, 128)
    obj_t = np.stack([rpn_targets(anc, b)[0] for b in Btr])
    del_t = np.stack([rpn_targets(anc, b)[1] for b in Btr])

    class RCNN(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.backbone = gluon.nn.HybridSequential()
                self.backbone.add(
                    gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                    gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                                    activation="relu"),
                    gluon.nn.Conv2D(32, 3, strides=2, padding=1,
                                    activation="relu"))
                self.rpn_obj = gluon.nn.Conv2D(N_ANCHOR * 2, 1)
                self.rpn_box = gluon.nn.Conv2D(N_ANCHOR * 4, 1)
                self.head = gluon.nn.HybridSequential()
                self.head.add(gluon.nn.Dense(64, activation="relu"),
                              gluon.nn.Dense(N_CLASSES))

        def features(self, x):
            return self.backbone(x)

    net = RCNN()
    net.initialize(mx.init.Xavier())
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    huber = gluon.loss.HuberLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def rois_from_rpn(feat, obj_logits, box_deltas, topk=8):
        """Proposal stage (the reference's _contrib_Proposal role): decode
        + NMS via the registered op, per image."""
        B = feat.shape[0]
        # Proposal expects BLOCK layout [A bg | A fg] (reference
        # proposal-inl.h: foreground scores are channels A:2A), while the
        # training head is (A, 2)-interleaved — reorder here
        scores = nd.softmax(obj_logits.reshape((B, N_ANCHOR, 2, FEAT, FEAT)),
                            axis=2)
        cls_prob = nd.concat(scores[:, :, 0], scores[:, :, 1], dim=1)
        im_info = nd.array(np.tile([IMG, IMG, 1.0], (B, 1)).astype(np.float32))
        rois = nd.Proposal(cls_prob, box_deltas, im_info,
                           rpn_pre_nms_top_n=64, rpn_post_nms_top_n=topk,
                           threshold=0.7, rpn_min_size=4,
                           scales=ANCHOR_SCALES, ratios=(1.0,),
                           feature_stride=STRIDE)
        return rois.reshape((-1, 5))       # (B*topk, 5) [bidx,x1,y1,x2,y2]

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            x = nd.array(Xtr[idx])
            obj = nd.array(obj_t[idx]); dl = nd.array(del_t[idx])
            boxes, labels = Btr[idx], Ltr[idx]
            with autograd.record():
                feat = net.features(x)
                ol = net.rpn_obj(feat)      # (B, 2A, Hf, Wf)
                bd = net.rpn_box(feat)      # (B, 4A, Hf, Wf)
                B = len(idx)
                # RPN losses on host-matched anchor targets
                ol_a = ol.reshape((B, N_ANCHOR, 2, FEAT, FEAT)) \
                         .transpose((0, 3, 4, 1, 2)).reshape((-1, 2))
                bd_a = bd.reshape((B, N_ANCHOR, 4, FEAT, FEAT)) \
                         .transpose((0, 3, 4, 1, 2)).reshape((-1, 4))
                objf = obj.reshape((-1,))
                care = (objf >= 0).astype("float32")
                l_obj = (sce(ol_a, nd.maximum(objf, nd.zeros_like(objf)))
                         * care).sum() / care.sum()
                posm = (objf == 1).astype("float32").reshape((-1, 1))
                l_box = (huber(bd_a, dl.reshape((-1, 4))) * posm.reshape((-1,))
                         ).sum() / posm.sum()
                # region stage: classify NMS'd proposals from the SAME
                # features (labels matched on host by IoU)
                rois = rois_from_rpn(feat, ol, bd)
                rois_np = rois.asnumpy()
                rlab = np.zeros(len(rois_np), np.float32)
                for r, (bidx, x1, y1, x2, y2) in enumerate(rois_np):
                    b = int(bidx)
                    if iou(np.array([[x1, y1, x2, y2]], np.float32),
                           boxes[b])[0] >= 0.5:
                        rlab[r] = labels[b]
                pooled = nd.ROIAlign(feat, rois, pooled_size=(3, 3),
                                     spatial_scale=1.0 / STRIDE)
                cls = net.head(pooled.reshape((pooled.shape[0], -1)))
                l_cls = sce(cls, nd.array(rlab)).mean()
                loss = l_obj + l_box + l_cls
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} loss {tot / (n // args.batch_size):.4f}")

    # detection eval: top proposal per image, IoU + class against truth
    feat = net.features(nd.array(Xte))
    ol, bd = net.rpn_obj(feat), net.rpn_box(feat)
    rois = rois_from_rpn(feat, ol, bd, topk=4)
    pooled = nd.ROIAlign(feat, rois, pooled_size=(3, 3),
                         spatial_scale=1.0 / STRIDE)
    cls = net.head(pooled.reshape((pooled.shape[0], -1))).asnumpy()
    rois_np = rois.asnumpy()
    hit = 0
    for b in range(len(Xte)):
        mine = [(r, cls[r]) for r in range(len(rois_np))
                if int(rois_np[r, 0]) == b]
        # best non-background proposal by head score
        best, best_s = None, -1e9
        for r, c in mine:
            k = int(np.argmax(c))
            if k != 0 and c[k] > best_s:
                best, best_s = (r, k), c[k]
        if best is None:
            continue
        r, k = best
        if k == Lte[b] and iou(rois_np[r:r + 1, 1:], Bte[b])[0] >= 0.5:
            hit += 1
    acc = hit / len(Xte)
    print(f"detection accuracy (IoU>=0.5 + class): {acc:.3f}")
    assert acc >= args.min_acc, f"detection accuracy {acc} < {args.min_acc}"
    print("RCNN_OK")


if __name__ == "__main__":
    main()
