#!/usr/bin/env python
"""Capsule network with dynamic routing (reference example/capsnet/
capsulenet.py — Sabour et al.: primary capsules from conv features,
class capsules computed by routing-by-agreement, margin loss on capsule
lengths).

Scaled to synthetic glyph digits. Everything that makes CapsNet CapsNet
is here: the squash nonlinearity, per-(primary, class) prediction
vectors u_hat = u W, three routing iterations where coupling logits
grow by agreement <u_hat, v>, and the m+/m- margin loss on output
capsule LENGTHS (class presence = vector norm, not a softmax). The
routing loop runs over nd ops under autograd — gradients flow through
the final coupling weights exactly as in the reference implementation.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 6
IMG = 16
PRIM_CAPS = 32          # number of primary capsules
PRIM_DIM = 8
OUT_DIM = 12


def make_data(rng, glyphs, n):
    y = rng.randint(0, N_CLASSES, n)
    X = glyphs[y] + 0.25 * rng.randn(n, 1, IMG, IMG).astype(np.float32)
    return X.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--routing-iters", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-acc", type=float, default=0.9)
    args = ap.parse_args()
    if args.routing_iters < 1:
        ap.error("--routing-iters must be >= 1")

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    glyphs = (rng.rand(N_CLASSES, 1, IMG, IMG) > 0.5).astype(np.float32)
    Xtr, ytr = make_data(rng, glyphs, 768)
    Xte, yte = make_data(rng, glyphs, 192)

    def squash(s, axis):
        """v = |s|^2/(1+|s|^2) * s/|s| — the capsule nonlinearity."""
        sq = nd.sum(s ** 2, axis=axis, keepdims=True)
        return (sq / (1.0 + sq)) * s / nd.sqrt(sq + 1e-9)

    class CapsNet(gluon.nn.Block):
        """Plain Block: the routing loop is data-dependent Python."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.conv = gluon.nn.Conv2D(32, 5, strides=2,
                                            activation="relu")
                self.prim = gluon.nn.Conv2D(PRIM_CAPS * PRIM_DIM, 3,
                                            strides=2)
                # W: (PRIM_TOTAL, N_CLASSES, OUT_DIM, PRIM_DIM) routing
                # transform, one matrix per (primary capsule, class)
                self.W = self.params.get(
                    "routing_weight",
                    shape=(PRIM_CAPS * 2 * 2, N_CLASSES,
                           OUT_DIM, PRIM_DIM),
                    init=mx.init.Xavier())

        def forward(self, x):
            B = x.shape[0]
            h = self.prim(self.conv(x))          # (B, C*D, 2, 2)
            n_prim = PRIM_CAPS * h.shape[2] * h.shape[3]
            u = h.reshape((B, PRIM_CAPS, PRIM_DIM, -1))
            u = u.transpose((0, 1, 3, 2)).reshape((B, n_prim, PRIM_DIM))
            u = squash(u, axis=2)                # primary capsule outputs
            W = self.W.data()                    # (P, K, OD, PD)
            # u_hat[b,p,k,:] = W[p,k] @ u[b,p]
            u_exp = u.reshape((B, n_prim, 1, 1, PRIM_DIM))
            u_hat = nd.sum(W.reshape((1, n_prim, N_CLASSES,
                                      OUT_DIM, PRIM_DIM)) * u_exp,
                           axis=4)               # (B, P, K, OD)

            # routing by agreement; logits updated OUTSIDE the grad tape
            # except the last pass, reference-style (detach u_hat for
            # the agreement updates)
            b_logit = nd.zeros((B, n_prim, N_CLASSES))
            u_hat_d = u_hat.detach()
            for it in range(args.routing_iters):
                c = nd.softmax(b_logit, axis=2)          # couplings
                uh = u_hat if it == args.routing_iters - 1 else u_hat_d
                s = nd.sum(c.reshape((B, n_prim, N_CLASSES, 1)) * uh,
                           axis=1)               # (B, K, OD)
                v = squash(s, axis=2)
                if it < args.routing_iters - 1:
                    agree = nd.sum(u_hat_d * v.reshape((B, 1, N_CLASSES,
                                                        OUT_DIM)), axis=3)
                    b_logit = b_logit + agree
            return nd.sqrt(nd.sum(v ** 2, axis=2) + 1e-9)   # lengths

    def margin_loss(lengths, y):
        """L = T max(0, m+ - |v|)^2 + 0.5 (1-T) max(0, |v| - m-)^2."""
        onehot = nd.one_hot(y, depth=N_CLASSES)
        pos = nd.maximum(0.9 - lengths, nd.zeros_like(lengths)) ** 2
        neg = nd.maximum(lengths - 0.1, nd.zeros_like(lengths)) ** 2
        return nd.mean(nd.sum(onehot * pos + 0.5 * (1 - onehot) * neg,
                              axis=1))

    np.random.seed(args.seed)
    net = CapsNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            with autograd.record():
                loss = margin_loss(net(nd.array(Xtr[idx])),
                                   nd.array(ytr[idx]))
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} margin loss {tot / (n // args.batch_size):.4f}")

    lengths = net(nd.array(Xte)).asnumpy()
    acc = float((lengths.argmax(1) == yte).mean())
    print(f"capsule-length accuracy {acc:.3f}")
    assert acc >= args.min_acc, acc
    print("CAPSNET_OK")


if __name__ == "__main__":
    main()
