#!/usr/bin/env python
"""Sparse matrix factorization (reference
example/sparse/matrix_factorization/train.py).

Learns user/item embeddings for a synthetic low-rank rating matrix from
a SPARSE sample of observed entries. Embedding gradients are row-sparse
by construction (only the rows of the sampled users/items update); the
optimizer's sparse-lazy path applies them without touching the full
tables — the reference's core sparse-training demo.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-users", type=int, default=200)
    ap.add_argument("--num-items", type=int, default=150)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    # ground-truth low-rank ratings
    U = rng.randn(args.num_users, args.rank).astype(np.float32)
    V = rng.randn(args.num_items, args.rank).astype(np.float32)
    n_obs = 4000
    users = rng.randint(0, args.num_users, n_obs)
    items = rng.randint(0, args.num_items, n_obs)
    ratings = (U[users] * V[items]).sum(1) + \
        rng.randn(n_obs).astype(np.float32) * 0.1

    user_emb = gluon.nn.Embedding(args.num_users, args.rank,
                                  sparse_grad=True)
    item_emb = gluon.nn.Embedding(args.num_items, args.rank,
                                  sparse_grad=True)
    user_emb.initialize(mx.init.Normal(0.1))
    item_emb.initialize(mx.init.Normal(0.1))
    params = gluon.parameter.ParameterDict()
    params.update(user_emb.collect_params())
    params.update(item_emb.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})
    l2 = gluon.loss.L2Loss()

    losses = []
    for ep in range(args.epochs):
        perm = rng.permutation(n_obs)
        tot, nb = 0.0, 0
        for i in range(0, n_obs, args.batch_size):
            idx = perm[i:i + args.batch_size]
            u = nd.array(users[idx].astype(np.float32))
            v = nd.array(items[idx].astype(np.float32))
            r = nd.array(ratings[idx])
            with autograd.record():
                pred = (user_emb(u) * item_emb(v)).sum(axis=1)
                loss = l2(pred, r)
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.mean().asnumpy())
            nb += 1
        losses.append(tot / nb)
        if ep % 4 == 0:
            print(f"epoch {ep}: mse loss {losses[-1]:.4f}")

    print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    print("SPARSE_MF_OK", losses[0], losses[-1])


if __name__ == "__main__":
    main()
