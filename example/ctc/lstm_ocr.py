#!/usr/bin/env python
"""LSTM + CTC sequence recognition (reference example/ctc/lstm_ocr_train.py,
which reads captcha images; here the 'OCR' task is synthesized so the
example is self-contained and deterministic).

Each sample is a variable-length digit string rendered as a strip of
fixed random glyph columns. The image's pixel columns are the LSTM's
time steps; per-step logits over {10 digits + blank} train with CTCLoss
(alignment-free — the model must discover WHERE each digit sits), and
greedy CTC decoding (collapse repeats, drop blanks) recovers the string.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_DIGITS = 10
GLYPH_W = 6        # pixel columns per rendered digit
IMG_H = 12         # pixel rows


def make_glyphs(rng):
    """A fixed random 'font': one (IMG_H, GLYPH_W) pattern per digit."""
    return (rng.rand(N_DIGITS, IMG_H, GLYPH_W) > 0.5).astype(np.float32)


def make_data(rng, glyphs, n, min_len, max_len):
    """Render digit strings into (n, T, IMG_H) column-major strips padded
    to the max width; labels padded with blank sentinel (=N_DIGITS)."""
    max_t = max_len * GLYPH_W
    X = np.zeros((n, max_t, IMG_H), np.float32)
    Y = np.full((n, max_len), N_DIGITS, np.float32)   # pad = blank class
    xlen = np.zeros((n,), np.float32)
    ylen = np.zeros((n,), np.float32)
    for i in range(n):
        k = rng.randint(min_len, max_len + 1)
        digits = rng.randint(0, N_DIGITS, k)
        strip = np.concatenate([glyphs[d] for d in digits], axis=1)  # (H, k*W)
        noisy = strip + 0.1 * rng.randn(*strip.shape)
        X[i, :k * GLYPH_W] = noisy.T
        Y[i, :k] = digits
        xlen[i], ylen[i] = k * GLYPH_W, k
    return X, Y, xlen, ylen


def greedy_decode(logits, length):
    """Collapse-repeats-then-drop-blank CTC decoding (blank = last)."""
    best = logits[:int(length)].argmax(axis=-1)
    out, prev = [], -1
    for t in best:
        if t != prev and t != N_DIGITS:
            out.append(int(t))
        prev = t
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--min-len", type=int, default=3)
    ap.add_argument("--max-len", type=int, default=5)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-acc", type=float, default=0.5)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    glyphs = make_glyphs(rng)
    Xtr, Ytr, xltr, yltr = make_data(rng, glyphs, 640, args.min_len,
                                     args.max_len)
    Xte, Yte, xlte, ylte = make_data(rng, glyphs, 160, args.min_len,
                                     args.max_len)

    class OCRNet(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.lstm = gluon.rnn.LSTM(args.hidden, layout="NTC",
                                           bidirectional=True)
                self.fc = gluon.nn.Dense(N_DIGITS + 1, flatten=False)

        def hybrid_forward(self, F, x):
            return self.fc(self.lstm(x))      # (B, T, 11), blank last

    net = OCRNet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            xl, yl = nd.array(xltr[idx]), nd.array(yltr[idx])
            with autograd.record():
                loss = ctc(net(x), y, xl, yl).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} ctc loss {tot / (n // args.batch_size):.3f}")

    logits = net(nd.array(Xte)).asnumpy()
    correct = sum(
        greedy_decode(logits[i], xlte[i]) ==
        [int(d) for d in Yte[i, :int(ylte[i])]]
        for i in range(len(Xte)))
    acc = correct / len(Xte)
    print(f"sequence accuracy: {acc:.3f}")
    assert acc >= args.min_acc, f"sequence accuracy {acc} < {args.min_acc}"
    print("LSTM_OCR_OK")


if __name__ == "__main__":
    main()
